"""Federated cross-shard Hubble (hubble/federation.py) + the chaos
acceptance journey: shard-kill + kvstore-flap on a LIVE sharded daemon
must yield an ordered flight-recorder timeline (trip -> degraded ->
fail-static -> rebuild -> recovered; kvstore degraded -> reconciling ->
recovered) and a federated observe answer carrying every shard's flows
with the degraded shard flagged fail-open.
"""

import json
import time

import numpy as np
import pytest

from cilium_tpu.hubble.federation import ShardedObserver
from cilium_tpu.hubble.filter import FlowFilter
from cilium_tpu.hubble.flow import FlowRecord
from cilium_tpu.monitor import MonitorHub


# --------------------------------------------------- fake shard plane

class FakePlane:
    """Minimal ShardedDatapath stand-in for observer unit tests."""

    def __init__(self, n_shards=2):
        self.n_shards = n_shards
        self.snaps = {k: [] for k in range(n_shards)}
        self.modes = {k: "ok" for k in range(n_shards)}
        self.dead = set()

    def shard_flow_snapshot(self, k, max_entries=4096):
        if k in self.dead:
            raise RuntimeError("device gone")
        return list(self.snaps[k])[:max_entries]

    def shard_flow_stats(self, k):
        return {"slots": 16, "occupied": len(self.snaps[k])}

    def flow_stats(self):
        return {"slots": 16 * self.n_shards,
                "occupied": sum(len(s) for s in self.snaps.values())}

    def shard_modes(self):
        return dict(self.modes)


def _agg_row(src, dst, dport, event, packets, nbytes, ls=100):
    return {"src-identity": src, "dst-identity": dst, "dport": dport,
            "proto": 6, "event": event, "packets": packets,
            "bytes": nbytes, "last-seen": ls}


class TestShardedObserver:
    def test_monitor_events_route_by_owning_shard(self):
        hub = MonitorHub()
        obs = ShardedObserver(node="n1", datapath=FakePlane(2))
        obs.attach_monitor(hub)
        hub.ingest_batch(np.array([-130, 0, 0, -130]),
                         np.array([0, 1, 2, 3]),
                         np.array([101, 102, 103, 104]),
                         np.array([80, 81, 82, 83]),
                         np.full(4, 6), np.full(4, 100))
        time.sleep(0.05)
        flows = obs.get_flows(limit=0)
        assert {(f["endpoint"], f["shard"]) for f in flows} == \
            {(0, 0), (1, 1), (2, 0), (3, 1)}
        # single-shard view
        only1 = obs.get_flows(shard=1, limit=0)
        assert {f["endpoint"] for f in only1} == {1, 3}
        with pytest.raises(ValueError):
            obs.get_flows(shard=7)

    def test_shared_cursor_merges_and_pages_forward(self):
        obs = ShardedObserver(node="n1", datapath=FakePlane(2))
        for i in range(6):
            obs.ingest(FlowRecord(seq=0, timestamp=float(i),
                                  node="n1", verdict="FORWARDED",
                                  endpoint=i))
        flows = obs.get_flows(limit=0)
        seqs = [f["seq"] for f in flows]
        assert seqs == sorted(seqs) == list(range(1, 7))
        assert obs.last_seq == 6
        # one cursor pages the MERGED stream across both stores
        page = obs.get_flows(since=3, limit=2)
        assert [f["seq"] for f in page] == [4, 5]

    def test_drain_delta_accounting(self):
        plane = FakePlane(2)
        obs = ShardedObserver(node="n1", datapath=plane)
        plane.snaps[0] = [_agg_row(201, 301, 80, 0, 5, 500)]
        plane.snaps[1] = [_agg_row(202, 302, 443, -130, 3, 300)]
        out = obs.drain()
        assert out["drained"] == 2
        flows = obs.get_flows(limit=0)
        assert len(flows) == 2
        drop = next(f for f in flows if f["shard"] == 1)
        assert drop["verdict"] == "DROPPED"
        assert drop["drop_reason"] != ""
        assert "+3 pkts" in drop["summary"]
        # unchanged counters drain nothing; moved counters drain the
        # delta only
        assert obs.drain()["drained"] == 0
        plane.snaps[0] = [_agg_row(201, 301, 80, 0, 9, 900)]
        out = obs.drain()
        assert out["drained"] == 1
        newest = obs.get_flows(limit=1)[0]
        assert "+4 pkts" in newest["summary"]

    def test_drain_fail_open_breaker_per_shard(self):
        plane = FakePlane(2)
        plane.snaps[0] = [_agg_row(201, 301, 80, 0, 5, 500)]
        plane.dead.add(1)
        obs = ShardedObserver(node="n1", datapath=plane)
        out = obs.drain()
        # the healthy shard drains; the dead one is a flagged error
        assert out["shards"]["0"]["status"] == "ok"
        assert out["shards"]["1"]["status"] == "error"
        obs.drain()  # second failure opens the breaker
        out = obs.drain()
        assert out["shards"]["1"]["status"] == "breaker-open"
        sts = {s["shard"]: s for s in obs.shard_statuses()}
        assert sts[1]["status"] == "drain-degraded"
        assert sts[0]["status"] == "ok"
        # heal: the breaker's half-open probe readmits the shard
        plane.dead.clear()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if obs.drain()["shards"]["1"]["status"] == "ok":
                break
            time.sleep(0.05)
        assert {s["shard"]: s["status"]
                for s in obs.shard_statuses()} == {0: "ok", 1: "ok"}

    def test_degraded_shard_flagged_fail_static(self):
        plane = FakePlane(2)
        plane.modes[1] = "degraded"
        obs = ShardedObserver(node="n1", datapath=plane)
        obs.ingest(FlowRecord(seq=0, timestamp=1.0, node="n1",
                              verdict="FORWARDED", endpoint=1))
        ans = obs.local_answer(limit=10)
        assert ans["partial"] is True
        sts = {s["shard"]: s["status"] for s in ans["shards"]}
        assert sts == {0: "ok", 1: "fail-static"}
        # the degraded shard's flows stay IN the answer (fail-open)
        assert any(f["shard"] == 1 for f in ans["flows"])

    def test_stats_aggregate_across_shards(self):
        """Satellite: hubble stats on sharded daemons must aggregate
        across shards instead of reporting the first observer's view:
        the store totals sum every shard store, the aggregation block
        is the mesh-wide flow_stats, and the hubble_* counters grow
        for traffic on EVERY shard."""
        from cilium_tpu.utils.metrics import (HUBBLE_DROPS,
                                              HUBBLE_FLOWS_PROCESSED)
        plane = FakePlane(2)
        obs = ShardedObserver(node="n1", datapath=plane)
        processed0 = HUBBLE_FLOWS_PROCESSED.total()
        drops0 = HUBBLE_DROPS.total()
        for k in (0, 1):
            obs.ingest(FlowRecord(
                seq=0, timestamp=1.0, node="n1", verdict="DROPPED",
                drop_reason="Policy denied", endpoint=k,
                src_identity=200 + k))
        assert HUBBLE_FLOWS_PROCESSED.total() == processed0 + 2
        assert HUBBLE_DROPS.total() == drops0 + 2
        st = obs.stats()
        assert st["store"]["ringed"] == 2
        assert st["aggregation"] == plane.flow_stats()
        assert set(st["per-shard"]) == {"0", "1"}

    def test_relay_propagates_shard_statuses(self):
        """Relay extension: a sharded peer's per-shard fail-open flags
        ride its node status, and a degraded shard makes the merged
        answer partial even though every peer answered."""
        from cilium_tpu.hubble.relay import HubbleRelay

        def local_fetch(query, since, limit):
            return {"flows": [{"seq": 1, "timestamp": 1.0,
                               "verdict": "FORWARDED", "shard": 1}],
                    "shards": [{"shard": 0, "status": "ok"},
                               {"shard": 1, "status": "fail-static"}]}

        relay = HubbleRelay(local_name="n1", local_fetch=local_fetch)
        out = relay.get_flows(limit=10)
        assert out["partial"] is True
        node = out["nodes"][0]
        assert node["status"] == "ok"
        assert node["shards"][1]["status"] == "fail-static"
        assert out["flows"][0]["shard"] == 1


# ------------------------------------------- chaos acceptance journey

class _FlakyKV:
    """BackendOperations pass-through with a blackhole switch: while
    engaged, every op raises (the etcd-blackhole analog without the
    proxy machinery)."""

    def __init__(self, inner):
        self._inner = inner
        self.blackholed = False

    def __getattr__(self, name):
        attr = getattr(self._inner, name)
        if name in ("get", "get_prefix", "list_prefix", "set",
                    "delete", "delete_prefix", "create_only",
                    "create_if_exists", "lock_path", "renew_lease"):
            def guarded(*a, **kw):
                if self.blackholed:
                    raise ConnectionError("kvstore blackholed")
                return attr(*a, **kw)
            return guarded
        return attr


def test_sharded_daemon_shard_kill_plus_kvstore_flap_timeline():
    """THE acceptance journey: on a live sharded daemon, a shard kill
    plus a kvstore flap produce one ordered flight-recorder timeline
    telling the whole story (trip -> degraded -> FAIL-STATIC ->
    rebuild -> recovery on the dataplane; degraded -> reconciling ->
    recovered on the control plane), and `hubble observe --federated`
    returns flows from ALL shards with the degraded shard flagged
    fail-open."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from cilium_tpu.cli import Client
    from cilium_tpu.daemon import Daemon
    from cilium_tpu.daemon.rest import APIServer
    from cilium_tpu.kvstore.memory import InMemoryBackend
    from cilium_tpu.observability.events import (
        EVENT_DATAPLANE_DEGRADED, EVENT_DATAPLANE_FAIL_STATIC,
        EVENT_DATAPLANE_REBUILD, EVENT_DATAPLANE_RECOVERED,
        EVENT_DATAPLANE_TRIP, EVENT_KVSTORE_DEGRADED,
        EVENT_KVSTORE_RECONCILING, EVENT_KVSTORE_RECOVERED, recorder)
    from cilium_tpu.policy.jsonio import rules_from_json
    from cilium_tpu.utils.faultinject import DeviceFaultInjector
    from cilium_tpu.utils.option import DaemonConfig

    flaky = _FlakyKV(InMemoryBackend())
    cfg = DaemonConfig(
        state_dir="", drift_audit_interval_s=0,
        ct_checkpoint_interval_s=0, dataplane_shards=2,
        hubble_flow_slots=1 << 8, hubble_drain_interval_s=0,
        supervisor_failure_threshold=1, supervisor_reset_s=0.05,
        supervisor_watchdog_s=5.0,
        enable_kvstore_survival=True, kvstore_failure_threshold=1,
        kvstore_probe_interval_s=0.05)
    d = Daemon(config=cfg, kvstore_backend=flaky)
    server = APIServer(d).start()
    try:
        d.endpoint_create(1, ipv4="10.200.0.10",
                          labels=["k8s:id=web"])
        d.endpoint_create(2, ipv4="10.200.0.11", labels=["k8s:id=db"])
        rules = rules_from_json(json.dumps([{
            "endpointSelector": {"matchLabels": {"id": "db"}},
            "ingress": [{
                "fromEndpoints": [{"matchLabels": {"id": "web"}}],
                "toPorts": [{"ports": [{"port": "5432",
                                        "protocol": "TCP"}]}]}],
            "labels": ["k8s:policy=t"]}]))
        rev = d.policy_add(rules)
        assert d.wait_for_policy_revision(rev, timeout=120)

        slot1 = d.endpoints.lookup(1).table_slot
        slot2 = d.endpoints.lookup(2).table_slot
        assert slot1 % 2 != slot2 % 2  # one endpoint per shard
        victim = slot2 % 2
        lane = d.datapath.serving()
        sup = lane.lanes[victim].supervisor
        web_ip = (10 << 24) | (200 << 16) | 10
        db_ip = (10 << 24) | (200 << 16) | 11

        def records(slots, dport, sport0):
            n = len(slots)
            return {
                "endpoint": np.asarray(slots, np.int32),
                "saddr": np.full(n, web_ip, np.uint32).view(np.int32),
                "daddr": np.full(n, db_ip, np.uint32).view(np.int32),
                "sport": (sport0 + np.arange(n)).astype(np.int32),
                "dport": np.full(n, dport, np.int32),
                "proto": np.full(n, 6, np.int32),
                "direction": np.zeros(n, np.int32),
                "tcp_flags": np.full(n, 0x02, np.int32),
                "is_fragment": np.zeros(n, np.int32),
                "length": np.full(n, 256, np.int32)}

        # traffic on BOTH shards -> both device flow tables populate
        both = records([slot1, slot2] * 8, 5432, 40000)
        t = lane.submit_records(
            {k: v.copy() for k, v in both.items()}, 16)
        t.result(timeout=120)
        assert t.error is None
        sup.oracle.refresh()
        # drain the per-shard device flow tables into the federated
        # stores: the complete flow plane, shard-attributed
        drained = d.hubble.drain()["drained"]
        assert drained > 0
        flows = d.hubble.get_flows(limit=0)
        assert {f["shard"] for f in flows} == {0, 1}

        seq0 = recorder.last_seq

        # ---- shard kill -------------------------------------------
        inj = DeviceFaultInjector()
        sup.install_fault_hook(inj)
        inj.fail_launch(times=1, fatal=True)
        kill = records([slot2] * 8, 5432, 41000)
        t = lane.submit_records(kill, 8)
        t.result(timeout=120)
        assert t.error is None          # fail-static, not denied
        assert sup.mode == "degraded"

        # federated observe WHILE degraded: flows from all shards,
        # the degraded shard flagged fail-open
        c = Client(server.base_url)
        out = c.get("/flows?federated=true&n=500")
        assert out["partial"] is True
        node = out["nodes"][0]
        shard_status = {s["shard"]: s["status"]
                        for s in node["shards"]}
        assert shard_status[victim] == "fail-static"
        assert shard_status[1 - victim] == "ok"
        assert {f.get("shard") for f in out["flows"]} >= {0, 1}
        # the plain sharded answer carries the same flags
        local = c.get("/flows?n=500")
        assert local["partial"] is True
        assert {s["shard"]: s["status"] for s in local["shards"]} \
            == shard_status
        # CLI: `hubble observe --shard K` scopes one fault domain
        import io
        import sys as _sys
        from cilium_tpu.cli import main as cli_main
        buf = io.StringIO()
        old_stdout = _sys.stdout
        _sys.stdout = buf
        try:
            rc = cli_main(["--api", server.base_url, "hubble",
                           "observe", "--shard", str(victim),
                           "--json", "-n", "500"])
        finally:
            _sys.stdout = old_stdout
        assert rc == 0
        rows = [json.loads(line) for line in
                buf.getvalue().strip().splitlines()
                if line.startswith("{")]
        assert rows and all(r["shard"] == victim for r in rows)

        # ---- kvstore flap -----------------------------------------
        flaky.blackholed = True
        deadline = time.time() + 30.0
        while d._kv_guard.mode != "degraded" and \
                time.time() < deadline:
            time.sleep(0.05)
        assert d._kv_guard.mode == "degraded"
        flaky.blackholed = False
        deadline = time.time() + 30.0
        while d._kv_guard.mode != "ok" and time.time() < deadline:
            time.sleep(0.05)
        assert d._kv_guard.mode == "ok"

        # ---- shard recovery ---------------------------------------
        inj.heal()
        deadline = time.time() + 30.0
        while sup.mode != "ok" and time.time() < deadline:
            time.sleep(0.05)
            lane.submit_records(
                records([slot2] * 8, 5432, 42000), 8).result(
                timeout=120)
        assert sup.mode == "ok"

        # ---- the ordered timeline ---------------------------------
        evs = recorder.events(since=seq0, limit=0)

        def first(typ, shard=None, **attrs):
            for e in evs:
                if e.type != typ:
                    continue
                if shard is not None and e.shard != shard:
                    continue
                if any(e.attrs.get(k) != v for k, v in attrs.items()):
                    continue
                return e.seq
            raise AssertionError(
                f"no {typ} (shard={shard}, {attrs}) in "
                f"{[(e.seq, e.type, e.shard) for e in evs]}")

        trip = first(EVENT_DATAPLANE_TRIP, shard=victim)
        degraded = first(EVENT_DATAPLANE_DEGRADED, shard=victim)
        static = first(EVENT_DATAPLANE_FAIL_STATIC, shard=victim)
        rebuild = first(EVENT_DATAPLANE_REBUILD, shard=victim,
                        result="ok")
        recovered = first(EVENT_DATAPLANE_RECOVERED, shard=victim)
        assert trip < degraded < static < rebuild < recovered, \
            [(e.seq, e.type, e.shard) for e in evs]
        kv_down = first(EVENT_KVSTORE_DEGRADED)
        kv_sync = first(EVENT_KVSTORE_RECONCILING)
        kv_up = first(EVENT_KVSTORE_RECOVERED)
        assert kv_down < kv_sync < kv_up
        # the dataplane and control-plane stories interleave in ONE
        # ordered record — the whole incident, `cilium-tpu events`
        assert degraded < kv_up and kv_down < recovered
        timeline = recorder.timeline(since=seq0)
        assert any("fail-static" in line for line in timeline)
        # recovered: the federated answer drops the flags
        out = c.get("/flows?n=500")
        assert {s["status"] for s in out["shards"]} == {"ok"}
    finally:
        server.shutdown()
        d.shutdown()
