"""Rule schema / selector / validation tests — mirrors reference
pkg/policy/api (selector_test.go, rule_validation_test.go, cidr_test.go,
entity_test.go) matrices.
"""

import pytest

from cilium_tpu.labels import LabelArray, parse_select_label
from cilium_tpu.policy import api
from cilium_tpu.policy.api import (CIDRRule, EgressRule, EndpointSelector,
                                   FQDNSelector, IngressRule, L7Rules,
                                   Operator, PolicyError, PortProtocol,
                                   PortRule, PortRuleHTTP, PortRuleKafka,
                                   Requirement, Rule, Service,
                                   compute_resultant_cidr_set, remove_cidrs)


def arr(*labels):
    return LabelArray.parse_select(*labels)


# --- selectors --------------------------------------------------------------

def test_selector_matches_basic():
    sel = EndpointSelector.parse("foo")
    assert sel.matches(arr("foo"))
    assert sel.matches(arr("foo", "bar"))
    assert not sel.matches(arr("bar"))


def test_selector_any_source_matches_all_sources():
    sel = EndpointSelector.parse("foo")
    assert sel.matches(LabelArray.parse("k8s:foo"))
    assert sel.matches(LabelArray.parse("container:foo"))


def test_selector_specific_source():
    sel = EndpointSelector.parse("k8s:foo")
    assert sel.matches(LabelArray.parse("k8s:foo"))
    assert not sel.matches(LabelArray.parse("container:foo"))


def test_selector_value_match():
    sel = EndpointSelector.parse("k8s:app=web")
    assert sel.matches(LabelArray.parse("k8s:app=web"))
    assert not sel.matches(LabelArray.parse("k8s:app=db"))


def test_wildcard_selector():
    assert api.WILDCARD_SELECTOR.matches(arr())
    assert api.WILDCARD_SELECTOR.matches(arr("anything"))
    assert api.WILDCARD_SELECTOR.is_wildcard()


def test_selector_match_expressions():
    sel = EndpointSelector(
        match_expressions=[Requirement(key="env", operator=Operator.IN,
                                       values=("prod", "staging"))])
    assert sel.matches(LabelArray.parse("k8s:env=prod"))
    assert not sel.matches(LabelArray.parse("k8s:env=dev"))

    sel = EndpointSelector(
        match_expressions=[Requirement(key="env",
                                       operator=Operator.NOT_IN,
                                       values=("prod",))])
    assert sel.matches(LabelArray.parse("k8s:env=dev"))
    assert sel.matches(arr("other"))  # absent key matches NotIn
    assert not sel.matches(LabelArray.parse("k8s:env=prod"))

    sel = EndpointSelector(
        match_expressions=[Requirement(key="env", operator=Operator.EXISTS)])
    assert sel.matches(LabelArray.parse("k8s:env=prod"))
    assert not sel.matches(arr("other"))

    sel = EndpointSelector(
        match_expressions=[Requirement(key="env",
                                       operator=Operator.DOES_NOT_EXIST)])
    assert not sel.matches(LabelArray.parse("k8s:env=prod"))
    assert sel.matches(arr("other"))


def test_selector_requires_values_for_in():
    sel = EndpointSelector(
        match_expressions=[Requirement(key="env", operator=Operator.IN)])
    with pytest.raises(PolicyError):
        sel.sanitize()


def test_selector_hashable_and_eq():
    a = EndpointSelector.parse("foo")
    b = EndpointSelector.parse("foo")
    c = EndpointSelector.parse("bar")
    assert a == b and hash(a) == hash(b)
    assert a != c
    assert len({a, b, c}) == 2


# --- entities ---------------------------------------------------------------

def test_entity_selectors():
    sels = api.entities_as_selectors([api.ENTITY_WORLD])
    assert sels[0].matches(LabelArray.parse("reserved:world"))
    sels = api.entities_as_selectors([api.ENTITY_ALL])
    assert sels[0].is_wildcard()
    sels = api.entities_as_selectors([api.ENTITY_HOST])
    assert sels[0].matches(LabelArray.parse("reserved:host"))


def test_entity_cluster_after_init():
    api.init_entities("mycluster")
    sels = api.entities_as_selectors([api.ENTITY_CLUSTER])
    assert any(s.matches(LabelArray.parse("reserved:host")) for s in sels)
    assert any(s.matches(LabelArray.parse(
        f"k8s:{api.POLICY_LABEL_CLUSTER}=mycluster")) for s in sels)
    api.init_entities("default")


def test_invalid_entity_rejected():
    rule = Rule(endpoint_selector=EndpointSelector.parse("foo"),
                ingress=[IngressRule(from_entities=["galaxy"])])
    with pytest.raises(PolicyError):
        rule.sanitize()


# --- CIDR -------------------------------------------------------------------

def test_cidr_sanitize():
    assert api.sanitize_cidr("10.0.0.0/8") == 8
    with pytest.raises(PolicyError):
        api.sanitize_cidr("10.0.0.0/40")
    with pytest.raises(PolicyError):
        api.sanitize_cidr("not-a-cidr")


def test_cidr_rule_except_must_be_contained():
    with pytest.raises(PolicyError):
        CIDRRule(cidr="10.0.0.0/8", except_cidrs=("192.168.0.0/16",)).sanitize()
    assert CIDRRule(cidr="10.0.0.0/8",
                    except_cidrs=("10.1.0.0/16",)).sanitize() == 8


def test_remove_cidrs():
    out = remove_cidrs(["10.0.0.0/8"], ["10.0.0.0/9"])
    assert out == ["10.128.0.0/9"]
    out = remove_cidrs(["10.0.0.0/8"], ["8.0.0.0/8"])
    assert out == ["10.0.0.0/8"]


def test_compute_resultant_cidr_set():
    out = compute_resultant_cidr_set([
        CIDRRule(cidr="10.0.0.0/24", except_cidrs=("10.0.0.128/25",))])
    assert out == ["10.0.0.0/25"]


def test_cidrs_as_selectors_world():
    sels = api.cidrs_as_selectors(["0.0.0.0/0"])
    assert any(s.matches(LabelArray.parse("reserved:world")) for s in sels)


# --- ports / L7 -------------------------------------------------------------

def test_port_protocol_sanitize():
    p = PortProtocol(port="80", protocol="tcp").sanitize()
    assert p.protocol == "TCP"
    p = PortProtocol(port="53").sanitize()
    assert p.protocol == "ANY"
    with pytest.raises(PolicyError):
        PortProtocol(port="99999", protocol="TCP").sanitize()
    with pytest.raises(PolicyError):
        PortProtocol(port="http", protocol="TCP").sanitize()
    with pytest.raises(PolicyError):
        PortProtocol(port="80", protocol="SCTP").sanitize()


def test_max_ports():
    pr = PortRule(ports=[PortProtocol(port=str(p), protocol="TCP")
                         for p in range(1, 43)])
    with pytest.raises(PolicyError):
        pr.sanitize(ingress=True)


def test_http_rule_regex_validation():
    PortRuleHTTP(path="/public/.*", method="GET").sanitize()
    with pytest.raises(PolicyError):
        PortRuleHTTP(path="/public/(").sanitize()


def test_http_rule_matching():
    r = PortRuleHTTP(method="GET", path="/public/.*")
    assert r.matches("GET", "/public/index.html")
    assert not r.matches("POST", "/public/index.html")
    assert not r.matches("GET", "/private/x")
    # empty rule matches everything
    assert PortRuleHTTP().matches("PUT", "/x")
    # header presence + value
    r = PortRuleHTTP(headers=("X-Token true",))
    assert r.matches("GET", "/", headers={"x-token": "true"})
    assert not r.matches("GET", "/", headers={})


def test_kafka_rule_validation():
    PortRuleKafka(api_key="produce", topic="logs").sanitize()
    with pytest.raises(PolicyError):
        PortRuleKafka(role="produce", api_key="fetch").sanitize()
    with pytest.raises(PolicyError):
        PortRuleKafka(api_key="not-a-key").sanitize()
    with pytest.raises(PolicyError):
        PortRuleKafka(role="observe").sanitize()
    with pytest.raises(PolicyError):
        PortRuleKafka(api_version="abc").sanitize()
    with pytest.raises(PolicyError):
        PortRuleKafka(topic="bad topic!").sanitize()


def test_kafka_role_expansion():
    """Reference: kafka.go:273-293 MapRoleToAPIKey."""
    r = PortRuleKafka(role="produce")
    assert set(r.api_keys_int) == {0, 3, 18}
    r = PortRuleKafka(role="consume")
    assert set(r.api_keys_int) == {1, 2, 3, 8, 9, 10, 11, 12, 13, 14, 18}
    assert r.matches_api_key(1)
    assert not r.matches_api_key(0)
    # no role/key: all allowed
    assert PortRuleKafka().matches_api_key(33)


def test_l7_rules_union_exclusive():
    with pytest.raises(PolicyError):
        L7Rules(http=[PortRuleHTTP()], kafka=[PortRuleKafka()]).sanitize()
    L7Rules(http=[PortRuleHTTP()]).sanitize()


# --- rule-level validation --------------------------------------------------

def test_l3_member_exclusivity_ingress():
    """Reference: rule_validation_test.go / TestL3PolicyRestrictions —
    combining FromCIDR and FromEndpoints is rejected."""
    r = Rule(endpoint_selector=EndpointSelector.parse("foo"), ingress=[
        IngressRule(from_cidr=["10.0.0.0/8"],
                    from_endpoints=[EndpointSelector.parse("bar")])])
    with pytest.raises(PolicyError):
        r.sanitize()


def test_from_cidr_with_ports_rejected():
    """Ingress CIDR+L4 unsupported (l3DependentL4Support=false for FromCIDR)."""
    r = Rule(endpoint_selector=EndpointSelector.parse("foo"), ingress=[
        IngressRule(from_cidr=["10.0.0.0/8"],
                    to_ports=[PortRule(ports=[
                        PortProtocol(port="80", protocol="TCP")])])])
    with pytest.raises(PolicyError):
        r.sanitize()


def test_to_cidr_with_ports_allowed():
    """Egress CIDR+L4 is supported (l3DependentL4Support=true for ToCIDR)."""
    r = Rule(endpoint_selector=EndpointSelector.parse("foo"), egress=[
        EgressRule(to_cidr=["10.0.0.0/8"],
                   to_ports=[PortRule(ports=[
                       PortProtocol(port="80", protocol="TCP")])])])
    r.sanitize()


def test_egress_member_exclusivity():
    r = Rule(endpoint_selector=EndpointSelector.parse("foo"), egress=[
        EgressRule(to_cidr=["10.0.0.0/8"],
                   to_services=[Service()])])
    with pytest.raises(PolicyError):
        r.sanitize()


def test_too_many_prefix_lengths():
    cidrs = [f"fd00::/{p}" for p in range(8, 50)]  # 42 distinct lengths
    r = Rule(endpoint_selector=EndpointSelector.parse("foo"), ingress=[
        IngressRule(from_cidr=cidrs)])
    with pytest.raises(PolicyError):
        r.sanitize()


def test_cilium_generated_labels_rejected():
    from cilium_tpu.labels import Label
    r = Rule(endpoint_selector=EndpointSelector.parse("foo"),
             labels=LabelArray([Label(key="x", source="cilium-generated")]))
    with pytest.raises(PolicyError):
        r.sanitize()


# --- FQDN -------------------------------------------------------------------

def test_fqdn_selector():
    FQDNSelector(match_name="cilium.io").sanitize()
    with pytest.raises(PolicyError):
        FQDNSelector().sanitize()
    with pytest.raises(PolicyError):
        FQDNSelector(match_name="*.cilium.io").sanitize()
    s = FQDNSelector(match_pattern="*.cilium.io")
    s.sanitize()
    assert s.matches("sub.cilium.io")
    assert s.matches("SUB.CILIUM.IO.")
    assert not s.matches("cilium.io")
    assert not s.matches("sub.cilium.io.evil.com")
    assert FQDNSelector(match_name="cilium.io").matches("cilium.io")
