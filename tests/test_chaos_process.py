"""Process-level chaos: kill -9 the agent under live verdict traffic.

The test/runtime/chaos.go analog composed from the round-4/5 pieces:
a REAL agent process (Daemon + REST + verdict service + periodic CT
checkpoints) serves verdict-service batches while a traffic thread
hammers it; the test SIGKILLs the agent mid-flight, restarts it on the
same state dir (the supervisor role), and asserts:

- zero wrong-allows at ANY point: a denied tuple never classifies as
  allowed — before the kill, during the dead window (connection
  errors, fine — closed is not open), or after restore;
- the established flow survives the kill via the periodic CT
  checkpoint (pinned-ctmap analog) — its non-SYN packets still forward
  after restart with no policy re-imported;
- pinned-map parity: a FRESH allowed flow also forwards after restore,
  before any policy re-import, because the checkpointed realized
  policy state is realized directly when the identity universe
  reproduced (daemon/state.go + bpffs semantics);
- after the orchestrator re-imports policy, the system converges and
  the L7 redirect (port 80 -> proxy) is re-established with a live
  listener.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from cilium_tpu.cli import Client
from cilium_tpu.compiler.lpm import ipv4_to_u32
from cilium_tpu.native import PKT_HEADER_DTYPE
from cilium_tpu.verdict_service import VerdictClient, VerdictServiceError

AGENT = os.path.join(os.path.dirname(__file__), "chaos_agent_proc.py")

WEB_IP, DB_IP = "10.0.0.21", "10.0.0.22"
SYN, ACK = 0x02, 0x10

RULES = [{
    "endpointSelector": {"matchLabels": {"id": "db"}},
    "ingress": [
        {"fromEndpoints": [{"matchLabels": {"id": "web"}}],
         "toPorts": [{"ports": [{"port": "5432", "protocol": "TCP"}]}]},
        {"toPorts": [{"ports": [{"port": "80", "protocol": "TCP"}],
                      "rules": {"http": [
                          {"method": "GET", "path": "/public.*"}]}}]},
    ],
    "labels": ["k8s:policy=chaos"],
}]


def _spawn(state_dir):
    proc = subprocess.Popen(
        [sys.executable, AGENT, str(state_dir), "0.2"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
    line = proc.stdout.readline()
    if not line:
        proc.kill()
        raise RuntimeError("agent subprocess died before reporting ports")
    return proc, json.loads(line)


def _recs(slot, sport, dport, flags=SYN, saddr=WEB_IP):
    recs = np.zeros(1, PKT_HEADER_DTYPE)
    recs["endpoint"] = slot
    recs["saddr"] = ipv4_to_u32(saddr)
    recs["daddr"] = ipv4_to_u32(DB_IP)
    recs["sport"] = sport
    recs["dport"] = dport
    recs["proto"] = 6
    recs["direction"] = 0
    recs["tcp_flags"] = flags
    recs["length"] = 100
    return recs


def _wait_verdict(vc, slot, dport, want_allow, timeout=60, base=51000):
    """Poll with FRESH source ports until the verdict matches."""
    deadline = time.time() + timeout
    k = 0
    while time.time() < deadline:
        v, _ = vc.classify(_recs(slot, base + (k % 9000), dport))
        if (int(v[0]) >= 0) == want_allow:
            return True
        k += 1
        time.sleep(0.05)
    return False


def test_kill9_restart_under_policy_churn(tmp_path):
    """Restart-under-churn: SIGKILL the agent while BOTH verdict
    traffic and policy churn (rule add/delete cycles) are in flight,
    restart on the same state dir, and assert:

    - restore_endpoints keeps the established flow forwarding (CT
      checkpoint + realized-state restore) with zero wrong-allows at
      any point;
    - the post-restore drift audit is green: the restored device
      tables replay bit-exact against the host policy oracles
      (POST /debug/drift-audit) both before and after the
      orchestrator re-imports policy.
    """
    state = tmp_path / "state"
    proc, info = _spawn(state)
    proc2 = None
    stop = threading.Event()
    wrong_allows = []
    churn_cycles = [0]
    ports = {"verdict": info["verdict_port"]}
    CHURN_RULE = [{
        "endpointSelector": {"matchLabels": {"id": "db"}},
        "ingress": [{"toPorts": [{"ports": [
            {"port": "6100", "protocol": "TCP"}]}]}],
        "labels": ["k8s:policy=churn"],
    }]
    try:
        c = Client(f"http://127.0.0.1:{info['api_port']}")
        c.put("/endpoint/1", {"ipv4": WEB_IP, "labels": ["k8s:id=web"]})
        c.put("/endpoint/2", {"ipv4": DB_IP, "labels": ["k8s:id=db"]})
        c.request("PUT", "/policy", RULES)
        slot = c.get("/endpoint/2")["table-slot"]

        vc = VerdictClient("127.0.0.1", ports["verdict"], timeout=120)
        assert _wait_verdict(vc, slot, 5432, True), "policy never applied"
        # the long-lived flow: SYN establishes CT, ACKs ride it
        v, _ = vc.classify(_recs(slot, 46001, 5432, SYN))
        assert int(v[0]) >= 0
        v, _ = vc.classify(_recs(slot, 46001, 5432, ACK))
        assert int(v[0]) >= 0
        established_at = time.time()

        def traffic():
            client = None
            k = 0
            while not stop.is_set():
                try:
                    if client is None:
                        client = VerdictClient(
                            "127.0.0.1", ports["verdict"], timeout=10)
                    v, _ = client.classify(
                        _recs(slot, 48000 + (k % 8000), 9999, SYN))
                    if int(v[0]) >= 0:
                        wrong_allows.append(("fresh-denied-allowed", k))
                    v, _ = client.classify(
                        _recs(slot, 46001, 5432, ACK))
                except (VerdictServiceError, OSError,
                        ConnectionError, socket.timeout):
                    if client is not None:
                        try:
                            client.close()
                        except Exception:  # noqa: BLE001
                            pass
                        client = None
                    stop.wait(0.05)
                k += 1
            if client is not None:
                try:
                    client.close()
                except Exception:  # noqa: BLE001
                    pass

        def policy_churn():
            """Rule add/delete cycles racing the kill window (REST
            failures during the dead window are the expected shape)."""
            cc = Client(f"http://127.0.0.1:{info['api_port']}")
            while not stop.is_set():
                try:
                    cc.request("PUT", "/policy", CHURN_RULE)
                    stop.wait(0.05)
                    cc.request("DELETE",
                               "/policy?labels=k8s:policy%3Dchurn")
                    churn_cycles[0] += 1
                except (Exception, SystemExit):  # noqa: BLE001 — the
                    # dead window (APIError subclasses SystemExit)
                    stop.wait(0.1)

        t = threading.Thread(target=traffic, daemon=True)
        t.start()
        tp = threading.Thread(target=policy_churn, daemon=True)
        tp.start()

        # churn + traffic against the healthy agent, and a CT
        # checkpoint that has captured the established flow
        deadline = time.time() + 20
        ct_path = os.path.join(str(state), "ct_state.npz")
        while time.time() < deadline and not (
                churn_cycles[0] >= 2 and os.path.exists(ct_path) and
                os.path.getmtime(ct_path) > established_at):
            time.sleep(0.05)
        assert churn_cycles[0] >= 2, "policy churn never ran"
        assert os.path.exists(ct_path), "no periodic CT checkpoint"

        # ---- chaos: SIGKILL mid-traffic, mid-churn ----
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=10)
        time.sleep(0.3)

        # ---- supervisor restart on the same state dir ----
        proc2, info2 = _spawn(state)
        assert info2["restored"] == 2
        ports["verdict"] = info2["verdict_port"]
        c2 = Client(f"http://127.0.0.1:{info2['api_port']}")
        vc2 = VerdictClient("127.0.0.1", ports["verdict"], timeout=120)

        # established flow survived the kill (restore_endpoints +
        # CT checkpoint), before any policy re-import
        v, _ = vc2.classify(_recs(slot, 46001, 5432, ACK))
        assert int(v[0]) >= 0, "established flow lost by kill -9"
        v, _ = vc2.classify(_recs(slot, 50002, 9999, SYN))
        assert int(v[0]) < 0, "restore admitted a denied flow"

        # the post-restore drift audit is green: the restored realized
        # state and the device tables tell one story
        rep = c2.request("POST", "/debug/drift-audit")
        assert rep["status"] in ("ok", "idle"), rep
        assert rep["checked"] > 0 or rep["status"] == "idle"

        # orchestrator re-imports; the system converges and the audit
        # stays green under the re-imported policy
        c2.request("PUT", "/policy", RULES)
        assert _wait_verdict(vc2, slot, 5432, True, base=52000)
        assert _wait_verdict(vc2, slot, 9999, False, base=53000)
        rep = c2.request("POST", "/debug/drift-audit")
        assert rep["status"] in ("ok", "idle"), rep

        stop.set()
        t.join(timeout=20)
        tp.join(timeout=20)
        assert not t.is_alive(), "traffic thread wedged"
        assert not wrong_allows, wrong_allows[:5]
        vc.close()
        vc2.close()
    finally:
        stop.set()
        for p in (proc, proc2):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait(timeout=10)


def test_kill9_under_traffic_restores_without_wrong_allows(tmp_path):
    state = tmp_path / "state"
    proc, info = _spawn(state)
    proc2 = None
    stop = threading.Event()
    wrong_allows = []
    ports = {"verdict": info["verdict_port"]}
    try:
        c = Client(f"http://127.0.0.1:{info['api_port']}")
        c.put("/endpoint/1", {"ipv4": WEB_IP, "labels": ["k8s:id=web"]})
        c.put("/endpoint/2", {"ipv4": DB_IP, "labels": ["k8s:id=db"]})
        c.request("PUT", "/policy", RULES)
        slot = c.get("/endpoint/2")["table-slot"]

        vc = VerdictClient("127.0.0.1", ports["verdict"], timeout=120)
        assert _wait_verdict(vc, slot, 5432, True), "policy never applied"
        v, _ = vc.classify(_recs(slot, 50001, 9999))
        assert int(v[0]) < 0, "denied port allowed before chaos"

        # the long-lived flow: SYN establishes CT, ACKs ride it
        v, _ = vc.classify(_recs(slot, 47001, 5432, SYN))
        assert int(v[0]) >= 0
        v, _ = vc.classify(_recs(slot, 47001, 5432, ACK))
        assert int(v[0]) >= 0
        established_at = time.time()

        def traffic():
            client = None
            k = 0
            while not stop.is_set():
                try:
                    if client is None:
                        client = VerdictClient("127.0.0.1",
                                               ports["verdict"],
                                               timeout=10)
                    v, _ = client.classify(
                        _recs(slot, 48000 + (k % 8000), 9999, SYN))
                    if int(v[0]) >= 0:
                        wrong_allows.append(("fresh-denied-allowed", k))
                    v, _ = client.classify(
                        _recs(slot, 47001, 5432, ACK))
                except (VerdictServiceError, OSError,
                        ConnectionError, socket.timeout):
                    # the dead window: connections fail CLOSED —
                    # reconnect against whatever port is current
                    if client is not None:
                        try:
                            client.close()
                        except Exception:  # noqa: BLE001
                            pass
                        client = None
                    stop.wait(0.05)
                k += 1
            if client is not None:
                try:
                    client.close()
                except Exception:  # noqa: BLE001
                    pass

        t = threading.Thread(target=traffic, daemon=True)
        t.start()
        time.sleep(1.0)  # live traffic against the healthy agent

        # make sure a periodic CT checkpoint has captured the flow
        ct_path = os.path.join(str(state), "ct_state.npz")
        deadline = time.time() + 15
        while time.time() < deadline and not (
                os.path.exists(ct_path) and
                os.path.getmtime(ct_path) > established_at):
            time.sleep(0.05)
        assert os.path.exists(ct_path), "no periodic CT checkpoint"
        assert os.path.getmtime(ct_path) > established_at

        # ---- chaos: SIGKILL mid-traffic ----
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=10)
        time.sleep(0.3)  # traffic thread hits the dead window

        # ---- supervisor restart on the same state dir ----
        proc2, info2 = _spawn(state)
        assert info2["restored"] == 2
        ports["verdict"] = info2["verdict_port"]
        c2 = Client(f"http://127.0.0.1:{info2['api_port']}")
        slot2 = c2.get("/endpoint/2")["table-slot"]
        assert slot2 == slot, "table slot moved across restore"
        vc2 = VerdictClient("127.0.0.1", ports["verdict"], timeout=120)

        # (a) established flow survived the SIGKILL via the periodic
        #     CT checkpoint — non-SYN continuation, no policy imported
        v, _ = vc2.classify(_recs(slot, 47001, 5432, ACK))
        assert int(v[0]) >= 0, "established flow lost by kill -9"
        # (b) denied stays denied through recovery
        v, _ = vc2.classify(_recs(slot, 50002, 9999, SYN))
        assert int(v[0]) < 0, "restore admitted a denied flow"
        # (c) pinned-map parity: FRESH allowed flow forwards from the
        #     restored realized state, before any policy re-import
        v, _ = vc2.classify(_recs(slot, 50003, 5432, SYN))
        assert int(v[0]) >= 0, "restore dropped an allowed flow"
        # (d) stale L7 redirects are scrubbed, not served: the
        #     checkpointed proxy port named the DEAD child's listener,
        #     so port-80 flows fail closed until policy re-import
        v, _ = vc2.classify(_recs(slot, 50004, 80, SYN))
        assert int(v[0]) < 0, "restore served a stale L7 redirect port"

        # ---- orchestrator re-imports policy; system converges ----
        c2.request("PUT", "/policy", RULES)
        assert _wait_verdict(vc2, slot, 5432, True, base=52000)
        assert _wait_verdict(vc2, slot, 9999, False, base=53000)

        # L7 re-sync: the old proxy child (orphaned by the SIGKILL)
        # must exit when its xDS stream died, and the restarted agent's
        # supervisor must spawn a successor that re-binds the redirect
        # port named by the port-80 verdict
        old_child = info.get("proxy_child_pid")
        if old_child:
            deadline = time.time() + 30
            while time.time() < deadline:
                try:
                    os.kill(old_child, 0)
                except ProcessLookupError:
                    break
                time.sleep(0.1)
            else:
                pytest.fail("orphaned proxy child still alive")
        deadline = time.time() + 60
        pport = -1
        k = 0
        bound = False
        while time.time() < deadline and not bound:
            v, _ = vc2.classify(_recs(slot, 54000 + k, 80, SYN))
            pport = int(v[0])
            if pport > 0:
                try:
                    s = socket.create_connection(("127.0.0.1", pport),
                                                 timeout=2)
                    s.close()
                    bound = True
                except OSError:
                    pass
            k += 1
            time.sleep(0.1)
        assert pport > 0, "L7 redirect not re-established"
        assert bound, "successor proxy child never re-bound the port"

        stop.set()
        t.join(timeout=20)
        assert not t.is_alive(), "traffic thread wedged"
        assert not wrong_allows, wrong_allows[:5]
        vc.close()
        vc2.close()
    finally:
        stop.set()
        for p in (proc, proc2):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait(timeout=10)
