"""Compiler + verdict engine vs the scalar oracle.

The "verifier analog" tier from the reference's test strategy: every
compiled artifact must (a) build, (b) agree with the pure-Python oracle
on randomized query matrices (policygen-style), (c) keep counters
consistent.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from cilium_tpu.compiler.hashtab import build_hash_table, hash_mix
from cilium_tpu.compiler.lpm import (compile_lpm, ipv4_to_u32, oracle_lpm,
                                     LPM_MISS)
from cilium_tpu.compiler.policy_tables import (CompiledPolicy,
                                               compile_endpoints,
                                               oracle_verdict, pack_key)
from cilium_tpu.datapath.verdict import (PacketBatch, VerdictEngine,
                                         VERDICT_ALLOW, VERDICT_DROP,
                                         VERDICT_DROP_FRAG,
                                         make_packet_batch)
from cilium_tpu.ops.hashtab_ops import batched_lookup, hash_mix_jnp
from cilium_tpu.ops.lpm_ops import lpm_lookup
from cilium_tpu.policy.mapstate import (EGRESS, INGRESS, PolicyKey,
                                        PolicyMapState, PolicyMapStateEntry)


def test_hash_host_device_lockstep():
    rng = np.random.default_rng(0)
    a = rng.integers(0, 2**32, 1000, dtype=np.uint32)
    b = rng.integers(0, 2**32, 1000, dtype=np.uint32)
    host = hash_mix(a, b)
    dev = np.asarray(hash_mix_jnp(jnp.asarray(a.view(np.int32)),
                                  jnp.asarray(b.view(np.int32))))
    np.testing.assert_array_equal(host, dev.view(np.uint32))


def test_hash_table_roundtrip():
    rng = np.random.default_rng(1)
    entries = {}
    while len(entries) < 500:
        ka = int(rng.integers(0, 2**32))
        kb = int(rng.integers(1, 2**32))
        entries[(ka, kb)] = int(rng.integers(0, 2**31))
    t = build_hash_table(entries)
    assert t.load <= 0.5 + 1e-9
    keys = list(entries)
    q_a = jnp.asarray(np.array([k[0] for k in keys], np.uint32).view(np.int32))
    q_b = jnp.asarray(np.array([k[1] for k in keys], np.uint32).view(np.int32))
    found, val, _ = batched_lookup(jnp.asarray(t.key_a), jnp.asarray(t.key_b),
                                   jnp.asarray(t.value), q_a, q_b, t.max_probe)
    assert bool(found.all())
    np.testing.assert_array_equal(np.asarray(val),
                                  np.array([entries[k] for k in keys]))
    # absent keys miss
    q_a2 = q_a + 7777
    found2, _, _ = batched_lookup(jnp.asarray(t.key_a), jnp.asarray(t.key_b),
                                  jnp.asarray(t.value), q_a2, q_b, t.max_probe)
    hit_keys = {(int(np.uint32(a) + np.uint32(7777)), int(np.uint32(b)))
                in entries
                for a, b in zip(np.asarray(q_a).view(np.uint32),
                                np.asarray(q_b).view(np.uint32))}
    # overwhelming majority should miss (collisions only if shifted key exists)
    assert int(np.asarray(found2).sum()) <= sum(hit_keys) + 0


def _random_map_state(rng, n_l4=50, n_l3=30, n_wild=5):
    state = PolicyMapState()
    for _ in range(n_l4):
        state[PolicyKey(identity=int(rng.integers(1, 70000)),
                        dest_port=int(rng.integers(1, 65536)),
                        nexthdr=int(rng.choice([6, 17])),
                        direction=int(rng.integers(0, 2)))] = \
            PolicyMapStateEntry(proxy_port=int(rng.choice([0, 0, 0, 12345])))
    for _ in range(n_l3):
        state[PolicyKey(identity=int(rng.integers(1, 70000)),
                        direction=int(rng.integers(0, 2)))] = \
            PolicyMapStateEntry()
    for _ in range(n_wild):
        state[PolicyKey(identity=0, dest_port=int(rng.integers(1, 65536)),
                        nexthdr=6, direction=INGRESS)] = \
            PolicyMapStateEntry(proxy_port=int(rng.choice([0, 10001])))
    return state


def test_verdict_engine_matches_oracle():
    rng = np.random.default_rng(42)
    states = [_random_map_state(rng) for _ in range(4)]
    compiled = compile_endpoints(states, revision=7)
    engine = VerdictEngine(compiled)

    # query matrix: hits (sampled from keys) + random probes
    eps, ids, dports, protos, dirs = [], [], [], [], []
    for e, st in enumerate(states):
        for k in list(st)[:40]:
            eps.append(e)
            ids.append(k.identity if k.identity else int(rng.integers(1, 70000)))
            dports.append(k.dest_port or int(rng.integers(1, 65536)))
            protos.append(k.nexthdr or 6)
            dirs.append(k.direction)
    for _ in range(300):
        eps.append(int(rng.integers(0, 4)))
        ids.append(int(rng.integers(1, 70000)))
        dports.append(int(rng.integers(1, 65536)))
        protos.append(int(rng.choice([6, 17])))
        dirs.append(int(rng.integers(0, 2)))

    pkt = make_packet_batch(eps, ids, dports, protos, dirs)
    verdict = np.asarray(engine(pkt))
    expected = np.array([
        oracle_verdict(states[e], i, p, pr, d)
        for e, i, p, pr, d in zip(eps, ids, dports, protos, dirs)])
    np.testing.assert_array_equal(verdict, expected)


def test_verdict_fragment_semantics():
    state = PolicyMapState({
        PolicyKey(identity=1000, dest_port=80, nexthdr=6,
                  direction=INGRESS): PolicyMapStateEntry(),
        PolicyKey(identity=2000, direction=INGRESS): PolicyMapStateEntry(),
    })
    compiled = compile_endpoints([state], revision=1)
    engine = VerdictEngine(compiled)
    pkt = make_packet_batch(
        endpoint=[0, 0, 0], identity=[1000, 2000, 1000],
        dport=[80, 80, 80], proto=[6, 6, 6], direction=[0, 0, 0],
        is_fragment=[1, 1, 0])
    v = np.asarray(engine(pkt))
    # fragment + only-L4 match => DROP_FRAG; fragment + L3 match => allow
    assert v[0] == VERDICT_DROP_FRAG
    assert v[1] == VERDICT_ALLOW
    assert v[2] == VERDICT_ALLOW


def test_verdict_counters():
    state = PolicyMapState({
        PolicyKey(identity=1000, dest_port=80, nexthdr=6,
                  direction=INGRESS): PolicyMapStateEntry(),
    })
    compiled = compile_endpoints([state], revision=1)
    engine = VerdictEngine(compiled)
    pkt = make_packet_batch(endpoint=[0] * 10, identity=[1000] * 10,
                            dport=[80] * 10, proto=[6] * 10,
                            direction=[0] * 10, length=[150] * 10)
    engine(pkt)
    engine(pkt)
    assert int(engine.counters.packets.sum()) == 20
    assert int(engine.counters.bytes.sum()) == 20 * 150


def test_three_stage_priority():
    """Exact beats L3-only beats wildcard — incl. proxy ports."""
    state = PolicyMapState({
        PolicyKey(identity=5, dest_port=80, nexthdr=6, direction=INGRESS):
            PolicyMapStateEntry(proxy_port=15000),
        PolicyKey(identity=5, direction=INGRESS): PolicyMapStateEntry(),
        PolicyKey(identity=0, dest_port=80, nexthdr=6, direction=INGRESS):
            PolicyMapStateEntry(proxy_port=16000),
    })
    compiled = compile_endpoints([state], revision=1)
    engine = VerdictEngine(compiled)
    pkt = make_packet_batch(
        endpoint=[0, 0, 0, 0],
        identity=[5, 5, 99, 99],
        dport=[80, 443, 80, 443],
        proto=[6, 6, 6, 6],
        direction=[0, 0, 0, 0])
    v = np.asarray(engine(pkt))
    assert v[0] == 15000        # exact, redirect
    assert v[1] == VERDICT_ALLOW  # L3-only fallback (no redirect)
    assert v[2] == 16000        # wildcard stage for unknown identity
    assert v[3] == VERDICT_DROP


def test_lpm_matches_oracle():
    rng = np.random.default_rng(3)
    prefixes = {"0.0.0.0/0": 2}  # world default
    for _ in range(80):
        addr = ".".join(str(int(rng.integers(0, 256))) for _ in range(4))
        plen = int(rng.integers(8, 33))
        prefixes[f"{addr}/{plen}"] = int(rng.integers(256, 65536))
    compiled = compile_lpm(prefixes)
    ips = [".".join(str(int(rng.integers(0, 256))) for _ in range(4))
           for _ in range(500)]
    # also test exact network addresses
    ips += [p.split("/")[0] for p in list(prefixes)[:50]]
    addrs = jnp.asarray(np.array([ipv4_to_u32(ip) for ip in ips],
                                 np.uint32).view(np.int32))
    found, val = lpm_lookup(jnp.asarray(compiled.masks),
                            jnp.asarray(compiled.key_a),
                            jnp.asarray(compiled.key_b),
                            jnp.asarray(compiled.value),
                            jnp.asarray(compiled.prefix_lens),
                            addrs, compiled.max_probe)
    expected = np.array([oracle_lpm(prefixes, ip) for ip in ips])
    np.testing.assert_array_equal(np.asarray(val), expected)
    assert bool(found.all())  # default route catches everything


def test_lpm_empty():
    compiled = compile_lpm({})
    found, val = lpm_lookup(jnp.asarray(compiled.masks),
                            jnp.asarray(compiled.key_a),
                            jnp.asarray(compiled.key_b),
                            jnp.asarray(compiled.value),
                            jnp.asarray(compiled.prefix_lens),
                            jnp.asarray(np.zeros(4, np.int32)),
                            compiled.max_probe)
    assert not bool(found.any())
    assert (np.asarray(val) == LPM_MISS).all()
