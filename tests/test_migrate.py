"""Checkpoint migration across agent versions.

Reference: bpf/cilium-map-migrate.c (584 LoC) + test/k8sT/Updates.go —
pinned state must survive agent upgrades via explicit layout
migration, and a downgrade must fail loudly rather than mis-parse.
Here the pinned-map analog is the endpoint checkpoint (ep_*.json);
device tables are derived state and rebuilt, so the checkpoints are
the whole migration surface.
"""

import json
import os

import pytest

from cilium_tpu.daemon import Daemon
from cilium_tpu.endpoint import Endpoint
from cilium_tpu.migrate import (CHECKPOINT_VERSION, MigrationError,
                                migrate_snapshot, migrate_state_dir)
from cilium_tpu.policy.mapstate import PolicyKey
from cilium_tpu.utils.option import DaemonConfig

V0 = {  # earliest layout: packed-string realized map, no version
    "id": 7,
    "ipv4": "10.9.0.7",
    "labels": ["k8s:app=old"],
    "state": "ready",
    "policy_revision": 3,
    "identity": 1234,
    "realized": {"1234:80:6:0": 0, "1234:443:6:0": 15001},
}

V1 = {  # dict entries, still unversioned
    "id": 8,
    "ipv4": "10.9.0.8",
    "labels": ["k8s:app=mid"],
    "state": "ready",
    "policy_revision": 4,
    "identity": 1235,
    "realized": [{"identity": 1235, "dest_port": 53, "nexthdr": 17,
                  "direction": 0, "proxy_port": 0}],
}


def test_migrate_v0_chain():
    out = migrate_snapshot(dict(V0))
    assert out["version"] == CHECKPOINT_VERSION
    assert out["family"] == 4
    entries = {(e["identity"], e["dest_port"]): e["proxy_port"]
               for e in out["realized"]}
    assert entries == {(1234, 80): 0, (1234, 443): 15001}


def test_migrate_v1_and_idempotent():
    out = migrate_snapshot(dict(V1))
    assert out["version"] == CHECKPOINT_VERSION
    assert migrate_snapshot(dict(out)) == out  # current is a no-op


def test_newer_version_refused():
    with pytest.raises(MigrationError):
        migrate_snapshot({"version": CHECKPOINT_VERSION + 1, "id": 1})


def test_restore_migrates_old_snapshots():
    ep = Endpoint.restore(dict(V0))
    assert ep.id == 7
    key = PolicyKey(identity=1234, dest_port=443, nexthdr=6, direction=0)
    assert ep.realized[key].proxy_port == 15001
    # current-format roundtrip still carries the version stamp
    ep2 = Endpoint.restore(ep.checkpoint())
    assert ep2.checkpoint()["version"] == CHECKPOINT_VERSION


def test_migrate_state_dir_in_place(tmp_path):
    d = str(tmp_path)
    for name, snap in (("ep_7.json", V0), ("ep_8.json", V1)):
        with open(os.path.join(d, name), "w") as f:
            json.dump(snap, f)
    # a current-format file and a garbage file round out the dir
    cur = migrate_snapshot(dict(V1))
    cur["id"] = 9
    with open(os.path.join(d, "ep_9.json"), "w") as f:
        json.dump(cur, f)
    with open(os.path.join(d, "ep_bad.json"), "w") as f:
        f.write("{not json")

    migrated, current, skipped = migrate_state_dir(d)
    assert (migrated, current) == (2, 1)
    assert skipped == ["ep_bad.json"]  # reported, not silently eaten
    for name in ("ep_7.json", "ep_8.json", "ep_9.json"):
        with open(os.path.join(d, name)) as f:
            assert json.load(f)["version"] == CHECKPOINT_VERSION
    assert os.path.exists(os.path.join(d, "ep_7.json.bak"))
    # idempotent second run
    assert migrate_state_dir(d) == (0, 3, ["ep_bad.json"])


def test_daemon_restores_across_versions(tmp_path):
    """The Updates.go scenario: a state dir written by older agent
    versions restores into a new agent; an unknown future version is
    skipped without blocking the rest."""
    state = str(tmp_path / "state")
    os.makedirs(state)
    with open(os.path.join(state, "ep_7.json"), "w") as f:
        json.dump(V0, f)
    with open(os.path.join(state, "ep_8.json"), "w") as f:
        json.dump(V1, f)
    with open(os.path.join(state, "ep_99.json"), "w") as f:
        json.dump({"version": 99, "id": 99}, f)

    d = Daemon(config=DaemonConfig(state_dir=state))
    try:
        n = d.restore_endpoints()
        assert n == 2
        assert d.endpoints.lookup(7) is not None
        assert d.endpoints.lookup(8) is not None
        assert d.endpoints.lookup(99) is None
        d.wait_for_policy_revision()
    finally:
        d.shutdown()


def test_cli_migrate_state(tmp_path, capsys):
    from cilium_tpu.cli import main
    d = str(tmp_path)
    with open(os.path.join(d, "ep_7.json"), "w") as f:
        json.dump(V0, f)
    assert main(["migrate-state", d]) == 0
    out = capsys.readouterr().out
    assert "migrated 1" in out
    with open(os.path.join(d, "ep_7.json")) as f:
        assert json.load(f)["version"] == CHECKPOINT_VERSION


def test_corrupt_snapshots_raise_migration_error():
    """Corrupt data surfaces as MigrationError (the skip-one-file
    contract), never a stray TypeError that aborts the restore."""
    for bad in ({"version": None, "id": 1},
                {"version": 0, "id": 1, "realized": [1, 2]},
                {"id": 1, "realized": {"1234:80:6:0": None}}):
        with pytest.raises(MigrationError):
            migrate_snapshot(dict(bad))


def test_cli_migrate_state_reports_skipped(tmp_path, capsys):
    from cilium_tpu.cli import main
    d = str(tmp_path)
    with open(os.path.join(d, "ep_99.json"), "w") as f:
        json.dump({"version": 99, "id": 99}, f)
    assert main(["migrate-state", d]) == 1  # nonzero: nothing migrated
    err = capsys.readouterr().err
    assert "SKIPPED" in err and "ep_99.json" in err
