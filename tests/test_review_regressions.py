"""Regression tests for review findings on the phase-1 semantics core."""

import time

import pytest

from cilium_tpu.identity import LocalIdentityAllocator
from cilium_tpu.labels import LabelArray, Labels
from cilium_tpu.policy.api import (EndpointSelector, FQDNSelector,
                                   IngressRule, L7Rules, PolicyError,
                                   PortProtocol, PortRule, PortRuleHTTP,
                                   Rule)
from cilium_tpu.policy.repository import Repository
from cilium_tpu.policy.trace import Port, SearchContext
from cilium_tpu.policy.api import Decision


def es(*labels):
    return EndpointSelector.parse(*labels)


def ctx(frm, to, dports=None):
    return SearchContext(from_labels=LabelArray.parse_select(*frm),
                         to_labels=LabelArray.parse_select(*to),
                         dports=list(dports or []))


def test_l7_rules_require_tcp():
    """L7 rules on ANY/UDP ports must be rejected at sanitize
    (reference: rule_validation.go:324) — otherwise the UDP side of an
    ANY expansion silently drops the L7 restriction (fail-open)."""
    for proto in ("ANY", "UDP"):
        r = Rule(endpoint_selector=es("a"), ingress=[
            IngressRule(to_ports=[PortRule(
                ports=[PortProtocol(port="80", protocol=proto)],
                rules=L7Rules(http=[PortRuleHTTP(path="/x")]))])])
        with pytest.raises(PolicyError):
            r.sanitize()


def test_fqdn_regex_linear_time():
    """The FQDN validation pattern must not backtrack catastrophically."""
    evil = "a" * 64 + "!"
    t0 = time.monotonic()
    with pytest.raises(PolicyError):
        FQDNSelector(match_name=evil).sanitize()
    assert time.monotonic() - t0 < 0.5


def test_identity_free_id_respects_cluster_bits():
    """With cluster_id>0 the free-ID scan must compare full numeric IDs,
    not base IDs, or live identities get reissued after wrap."""
    a = LocalIdentityAllocator(cluster_id=1)
    first, _ = a.allocate(Labels.from_model(["k8s:app=first"]))
    # Force the counter to wrap back onto first's base ID.
    a._next = first.id & 0xFFFF
    second, _ = a.allocate(Labels.from_model(["k8s:app=second"]))
    assert second.id != first.id
    assert a.lookup_by_id(first.id).labels is first.labels


def test_wildcard_l3_peer_added_to_filter_endpoints():
    """An L3-only allow overlapping an L7 filter must add the peer to the
    filter's endpoint list so L4 coverage checks allow it
    (reference: repository.go:162)."""
    repo = Repository()
    repo.add(Rule(endpoint_selector=es("bar"), ingress=[
        IngressRule(from_endpoints=[es("l3peer")])]))
    repo.add(Rule(endpoint_selector=es("bar"), ingress=[
        IngressRule(from_endpoints=[es("l7peer")],
                    to_ports=[PortRule(
                        ports=[PortProtocol(port="80", protocol="TCP")],
                        rules=L7Rules(http=[PortRuleHTTP(path="/private")]))])]))
    l4 = repo.resolve_l4_ingress_policy(ctx([], ["bar"]))
    flt = l4["80/TCP"]
    assert flt.matches_labels(LabelArray.parse_select("l3peer"))
    assert l4.contains_all_l3_l4(LabelArray.parse_select("l3peer"),
                                 [Port(80, "TCP")]) == Decision.ALLOWED


def test_wildcard_l3_overwrites_restrictive_l7():
    """A later L3-only allow must widen an existing restrictive L7 entry
    for the same selector to allow-all (reference overwrites)."""
    repo = Repository()
    repo.add(Rule(endpoint_selector=es("bar"), ingress=[
        IngressRule(from_endpoints=[es("peer")],
                    to_ports=[PortRule(
                        ports=[PortProtocol(port="80", protocol="TCP")],
                        rules=L7Rules(http=[PortRuleHTTP(path="/only")]))])]))
    repo.add(Rule(endpoint_selector=es("bar"), ingress=[
        IngressRule(from_endpoints=[es("peer")])]))
    l4 = repo.resolve_l4_ingress_policy(ctx([], ["bar"]))
    flt = l4["80/TCP"]
    sel = es("peer")
    assert flt.l7_rules_per_ep[sel].http == [PortRuleHTTP()]


def test_any_proto_l4_allow_wildcards_l7():
    """A port-ANY L4-only allow must wildcard L7 on the TCP filter
    (ANY expands to TCP/UDP in the wildcard pass too)."""
    repo = Repository()
    repo.add(Rule(endpoint_selector=es("bar"), ingress=[
        IngressRule(from_endpoints=[es("x")],
                    to_ports=[PortRule(ports=[
                        PortProtocol(port="80", protocol="ANY")])])]))
    repo.add(Rule(endpoint_selector=es("bar"), ingress=[
        IngressRule(from_endpoints=[es("y")],
                    to_ports=[PortRule(
                        ports=[PortProtocol(port="80", protocol="TCP")],
                        rules=L7Rules(http=[PortRuleHTTP(path="/p")]))])]))
    l4 = repo.resolve_l4_ingress_policy(ctx([], ["bar"]))
    flt = l4["80/TCP"]
    rules = flt.l7_rules_per_ep.get_relevant_rules(
        LabelArray.parse_select("x"))
    assert rules.http == [PortRuleHTTP()]
