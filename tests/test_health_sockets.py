"""Health probing over real sockets, across processes.

Reference: cilium-health (pkg/health/server/prober.go:139,229) — the
prober issues real network probes against each node's health endpoint;
a dead node's paths go unhealthy.  VERDICT weak item: the prober was
simulation-only by default and no test wired real sockets across the
two-daemon subprocess setup.  This does: a peer agent process serves a
HealthResponder and registers in the shared kvstore; the local
prober's TCP probes succeed against the live process and fail after
kill -9.
"""

import json
import os
import signal
import subprocess
import sys
import time

from cilium_tpu.health import (HealthProber, HealthResponder,
                               make_tcp_probe)
from cilium_tpu.kvstore.server import KVStoreServer
from cilium_tpu.kvstore.remote import RemoteBackend

HERE = os.path.dirname(os.path.abspath(__file__))


def test_tcp_probe_roundtrip_in_process():
    responder = HealthResponder().start()
    probe = make_tcp_probe(lambda ip: responder.port)
    ok, lat = probe("icmp", "127.0.0.1")
    assert ok and lat < 2
    ok, lat = probe("http", "127.0.0.1")
    assert ok
    responder.shutdown()
    ok, _ = probe("icmp", "127.0.0.1")
    assert not ok


def test_cross_process_probe_and_node_death():
    server = KVStoreServer(port=0).start()
    proc = subprocess.Popen(
        [sys.executable, os.path.join(HERE, "health_proc.py"),
         str(server.port), "peer-node"],
        stdout=subprocess.PIPE, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    kv = None
    try:
        info = json.loads(proc.stdout.readline())
        health_port = info["health_port"]

        # discover the peer through the shared kvstore node registry,
        # like the reference prober walks GetNodes
        from cilium_tpu.node import NodeRegistry
        kv = RemoteBackend(port=server.port, lease_ttl=10.0)
        reg = NodeRegistry(kv)
        deadline = time.time() + 15
        while not reg.nodes() and time.time() < deadline:
            time.sleep(0.1)
        nodes = reg.nodes()
        assert nodes and nodes[0].name == "peer-node"

        prober = HealthProber(
            nodes_fn=lambda: [(n.full_name, n.get_node_ip())
                              for n in reg.nodes()],
            probe_fn=make_tcp_probe(lambda ip: health_port),
            interval=3600)  # we drive probes manually
        prober.probe_once()
        st = prober.status()["default/peer-node"]
        assert st["healthy"] and st["icmp"] and st["http"]
        assert st["latency-seconds"]["http"] < 2

        # node death: kill -9, probes fail on the next sweep
        os.kill(info["pid"], signal.SIGKILL)
        proc.wait(10)
        prober.probe_once()
        st = prober.status()["default/peer-node"]
        assert not st["healthy"] and not st["icmp"]
        assert "default/peer-node" in prober.unhealthy_nodes()
        prober.shutdown()
    finally:
        try:
            proc.kill()
        except OSError:
            pass
        if kv is not None:
            kv.close()
        server.shutdown()
