"""Subprocess agent for the cross-process health-probe test.

Runs a Daemon connected to the shared TCP kvstore, registers its node,
and serves a real HealthResponder socket — the cilium-health per-node
endpoint.  Prints one JSON line with the responder port, then sleeps
until killed (kill -9 models node death: probes start failing).

Usage: python tests/health_proc.py <kv_port> <node_name>
"""

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from cilium_tpu.daemon import Daemon  # noqa: E402
from cilium_tpu.health import HealthResponder  # noqa: E402
from cilium_tpu.kvstore.remote import RemoteBackend  # noqa: E402
from cilium_tpu.node import Node, NodeAddress  # noqa: E402
from cilium_tpu.utils.option import DaemonConfig  # noqa: E402


def main() -> None:
    kv_port = int(sys.argv[1])
    node_name = sys.argv[2]
    kv = RemoteBackend(port=kv_port, lease_ttl=10.0)
    d = Daemon(config=DaemonConfig(), kvstore_backend=kv,
               node_name=node_name)
    responder = HealthResponder().start()
    d.node_registry.register_local(Node(
        name=node_name,
        addresses=[NodeAddress("InternalIP", "127.0.0.1")],
        ipv4_alloc_cidr="10.66.1.0/24"))
    print(json.dumps({"health_port": responder.port,
                      "pid": os.getpid()}), flush=True)
    time.sleep(3600)


if __name__ == "__main__":
    main()
