"""Regression tests for the round-3 ADVICE.md findings.

Each test fails on the pre-fix code:

1. (high) l7/socket_proxy.py HTTP framing accepted a negative /
   non-numeric Content-Length and last-wins duplicate headers —
   request-smuggling: pipelined bytes after an allowed head reached the
   upstream unchecked (buf[:-N] mis-framing).
2. (med) Kafka CorrelationCache was proxy-wide; colliding correlation
   ids across client connections mis-attributed response-path access
   logs (reference allocates per connection, pkg/proxy/kafka.go:335).
3. (med) kvstore server spawned one unbounded daemon thread per frame
   and mutated locks/watches without synchronization against finish();
   a lock granted after the connection died was stranded until lease
   expiry.
4. (med) kvstore RemoteBackend._call defaulted to an infinite wait — a
   dead server dispatch thread wedged the calling daemon forever.
"""

import socket
import socketserver
import struct
import threading
import time

import pytest

from cilium_tpu.kvstore.remote import RemoteBackend, RemoteError
from cilium_tpu.kvstore.server import (KVStoreServer, MAX_INFLIGHT,
                                       recv_frame, send_frame)
from cilium_tpu.l7.kafka import KafkaPolicyEngine
from cilium_tpu.l7.socket_proxy import ListenerContext, SocketProxy
from cilium_tpu.policy.api import PortRuleHTTP, PortRuleKafka
from cilium_tpu.l7.http import HTTPPolicyEngine
from cilium_tpu.proxy import AccessLog


class _Upstream(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, handler_fn):
        self.received = []
        self.handler_fn = handler_fn
        super().__init__(("127.0.0.1", 0), _UpHandler)
        threading.Thread(target=self.serve_forever, daemon=True).start()

    @property
    def port(self):
        return self.server_address[1]


class _UpHandler(socketserver.BaseRequestHandler):
    def handle(self):
        while True:
            try:
                data = self.request.recv(65536)
            except OSError:
                return
            if not data:
                return
            self.server.received.append(data)
            reply = self.server.handler_fn(data)
            if reply:
                self.request.sendall(reply)


def _connect(port):
    s = socket.create_connection(("127.0.0.1", port), timeout=5)
    s.settimeout(5)
    return s


def _drain(sock, timeout=2):
    """Read until EOF/reset/timeout; returns whatever arrived."""
    deadline = time.time() + timeout
    sock.settimeout(0.2)
    buf = b""
    while time.time() < deadline:
        try:
            chunk = sock.recv(65536)
        except socket.timeout:
            continue
        except OSError:
            break
        if not chunk:
            break
        buf += chunk
    return buf


@pytest.fixture()
def proxy():
    log = AccessLog()
    sp = SocketProxy(access_log=log)
    sp.test_log = log
    yield sp
    sp.shutdown()


# ------------------------------------------------- 1. HTTP CL smuggling

def _http_ctx(upstream, paths="/public/.*"):
    engine = HTTPPolicyEngine([PortRuleHTTP(path=paths)])
    return ListenerContext(
        redirect_id="r:ingress:TCP:80", parser_type="http",
        orig_dst=lambda peer: ("127.0.0.1", upstream.port),
        http_engine_for=lambda peer: engine)


def test_http_negative_content_length_fails_closed(proxy):
    """An allowed head with CL:-13 followed by a pipelined disallowed
    request: old code skipped the body read, mis-framed buf[:-13], and
    forwarded the smuggled bytes upstream unchecked."""
    upstream = _Upstream(lambda data: None)
    port = proxy.start_listener(0, _http_ctx(upstream))
    c = _connect(port)
    try:
        c.sendall(b"POST /public/a HTTP/1.1\r\nHost: h\r\n"
                  b"Content-Length: -13\r\n\r\n"
                  b"GET /secret HTTP/1.1\r\n\r\n")
        _drain(c)
    finally:
        c.close()
        upstream.shutdown()
    blob = b"".join(upstream.received)
    assert b"secret" not in blob
    assert b"/public/a" not in blob  # whole exchange failed closed


def test_http_duplicate_content_length_fails_closed(proxy):
    """CL.CL desync: last-wins dict made this proxy frame with 26 while
    an upstream honoring the first CL framed with 0."""
    upstream = _Upstream(lambda data: None)
    port = proxy.start_listener(0, _http_ctx(upstream))
    c = _connect(port)
    try:
        c.sendall(b"POST /public/a HTTP/1.1\r\nHost: h\r\n"
                  b"Content-Length: 0\r\n"
                  b"Content-Length: 26\r\n\r\n"
                  b"DELETE /secret HTTP/1.1\r\n\r\n")
        _drain(c)
    finally:
        c.close()
        upstream.shutdown()
    assert b"secret" not in b"".join(upstream.received)
    assert not upstream.received


def test_http_non_numeric_content_length_fails_closed(proxy):
    upstream = _Upstream(lambda data: None)
    port = proxy.start_listener(0, _http_ctx(upstream))
    # (OWS around the value is stripped at parse — that form is
    # unambiguous; these are the parser-dependent ones)
    for bad in (b"+5", b"5x", b"0x10", b"5 5", b""):
        c = _connect(port)
        try:
            c.sendall(b"GET /public/a HTTP/1.1\r\nHost: h\r\n"
                      b"Content-Length: " + bad + b"\r\n\r\nhello")
            _drain(c)
        finally:
            c.close()
    upstream.shutdown()
    assert not upstream.received


def test_http_valid_content_length_still_forwards(proxy):
    ok = b"HTTP/1.1 200 OK\r\ncontent-length: 2\r\n\r\nok"
    upstream = _Upstream(lambda data: ok)
    port = proxy.start_listener(0, _http_ctx(upstream))
    c = _connect(port)
    try:
        c.sendall(b"POST /public/a HTTP/1.1\r\nHost: h\r\n"
                  b"Content-Length: 5\r\n\r\nhello")
        assert b"200 OK" in _drain(c)
    finally:
        c.close()
        upstream.shutdown()
    assert b"hello" in b"".join(upstream.received)


# ------------------------------------- 2. Kafka per-connection cache

def _kafka_request(corr, topic, client=b"cli"):
    body = struct.pack(">hhi", 0, 0, corr)          # produce v0
    body += struct.pack(">h", len(client)) + client
    body += struct.pack(">hi", 1, 1000)             # acks, timeout
    body += struct.pack(">i", 1)                    # one topic
    body += struct.pack(">h", len(topic)) + topic
    body += struct.pack(">i", 0)                    # partitions: []
    return struct.pack(">i", len(body)) + body


def test_kafka_correlation_cache_is_per_connection(proxy):
    """Two clients, same correlation id 7, different topics.  The broker
    holds replies until both requests arrive, so with a proxy-wide cache
    the second put overwrites the first and one response gets the wrong
    topics while the other correlates to nothing."""
    both_in = threading.Event()
    count = [0]
    mu = threading.Lock()

    def broker(data):
        with mu:
            count[0] += 1
            if count[0] >= 2:
                both_in.set()
        both_in.wait(5)
        out = b""
        while len(data) >= 4:
            (size,) = struct.unpack_from(">i", data, 0)
            (corr,) = struct.unpack_from(">i", data, 8)
            payload = struct.pack(">ih", corr, 0)
            out += struct.pack(">i", len(payload)) + payload
            data = data[4 + size:]
        return out

    upstream = _Upstream(broker)
    engine = KafkaPolicyEngine([
        PortRuleKafka(api_key="produce", topic="topic-a"),
        PortRuleKafka(api_key="produce", topic="topic-b")])
    ctx = ListenerContext(
        redirect_id="k:egress:TCP:9092", parser_type="kafka",
        orig_dst=lambda peer: ("127.0.0.1", upstream.port),
        kafka_engine_for=lambda peer: engine)
    port = proxy.start_listener(0, ctx)
    a, b = _connect(port), _connect(port)
    try:
        a.sendall(_kafka_request(7, b"topic-a"))
        b.sendall(_kafka_request(7, b"topic-b"))
        ra, rb = _drain(a), _drain(b)
        assert ra and rb  # both clients got their broker reply
    finally:
        a.close()
        b.close()
        upstream.shutdown()
    responses = [e for e in proxy.test_log.tail()
                 if e.verdict == "response"]
    topics = sorted(tuple(e.info["topics"]) for e in responses)
    assert topics == [("topic-a",), ("topic-b",)]


# -------------------------------- 3. kvstore server dispatch bounding

def _raw_frames(port, frames, hold=True):
    """Open a raw client, send hello + the given request frames."""
    s = socket.create_connection(("127.0.0.1", port), timeout=5)
    send_frame(s, {"id": 1, "op": "hello", "ttl": 30})
    resp = recv_frame(s)
    assert resp and resp["ok"]
    for i, fr in enumerate(frames, start=2):
        fr = dict(fr)
        fr["id"] = i
        send_frame(s, fr)
    return s


def test_server_dispatch_thread_count_is_bounded():
    """Flood 4×MAX_INFLIGHT blocking lock requests on one connection:
    dispatch threads must plateau at MAX_INFLIGHT, not one per frame."""
    server = KVStoreServer(port=0).start()
    holder = RemoteBackend(port=server.port, lease_ttl=30)
    lock = holder.lock_path("/flood", timeout=5)
    before = threading.active_count()
    flood = _raw_frames(
        server.port,
        [{"op": "lock", "path": "/flood", "timeout": 20}] * (
            MAX_INFLIGHT * 4))
    time.sleep(1.0)  # let the server read + dispatch what it will
    grown = threading.active_count() - before
    try:
        assert grown <= MAX_INFLIGHT + 8, \
            f"dispatch threads unbounded: +{grown}"
    finally:
        flood.close()
        lock.unlock()
        holder.close()
        server.shutdown()


def test_lock_granted_after_connection_death_is_released():
    """B waits for a lock, dies; A unlocks; the grant must not be
    stranded in the dead connection's lock table — C acquires fast
    (old code: stranded until B's 30s lease expired)."""
    server = KVStoreServer(port=0).start()
    a = RemoteBackend(port=server.port, lease_ttl=30)
    lock_a = a.lock_path("/contended", timeout=5)

    b = RemoteBackend(port=server.port, lease_ttl=30)
    b_started = threading.Event()

    def b_waits():
        b_started.set()
        try:
            b.lock_path("/contended", timeout=20)
        except (RemoteError, Exception):  # noqa: BLE001 — conn dies
            pass

    threading.Thread(target=b_waits, daemon=True).start()
    b_started.wait(5)
    time.sleep(0.3)      # B's lock request is now parked server-side
    b.close()            # kill B mid-wait
    time.sleep(0.2)      # server runs finish() for B's connection
    lock_a.unlock()      # grant goes to B's dead dispatch thread

    c = RemoteBackend(port=server.port, lease_ttl=30)
    t0 = time.time()
    lock_c = c.lock_path("/contended", timeout=3)
    elapsed = time.time() - t0
    lock_c.unlock()
    for cli in (a, c):
        cli.close()
    server.shutdown()
    assert elapsed < 2.0, f"lock stranded on dead connection ({elapsed:.1f}s)"


# ----------------------------------------- 4. finite remote timeouts

class _BlackholeServer:
    """Speaks hello, then swallows every subsequent request."""

    def __init__(self):
        self._srv = socket.socket()
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("127.0.0.1", 0))
        self._srv.listen(1)
        self.port = self._srv.getsockname()[1]
        threading.Thread(target=self._run, daemon=True).start()

    def _run(self):
        conn, _ = self._srv.accept()
        req = recv_frame(conn)
        send_frame(conn, {"id": req["id"], "ok": True, "session": "s"})
        while recv_frame(conn) is not None:
            pass  # swallow

    def close(self):
        self._srv.close()


def test_remote_call_times_out_instead_of_hanging():
    bh = _BlackholeServer()
    client = RemoteBackend(port=bh.port, lease_ttl=30, call_timeout=1.0)
    t0 = time.time()
    with pytest.raises(RemoteError, match="timed out"):
        client.get("/k")
    assert time.time() - t0 < 5.0
    client.close()
    bh.close()


def test_remote_default_call_timeout_is_finite():
    from cilium_tpu.kvstore.remote import DEFAULT_CALL_TIMEOUT
    assert DEFAULT_CALL_TIMEOUT is not None
    assert 0 < DEFAULT_CALL_TIMEOUT < float("inf")
