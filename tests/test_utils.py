"""Tests for cross-cutting utils: controller, trigger, completion,
revert, backoff, option, spanstat, metrics.

Modeled on the reference's pkg/{controller,trigger,completion,revert,
option}/..._test.go behaviors.
"""

import threading
import time

import pytest

from cilium_tpu.utils import (Completion, Controller, ControllerManager,
                              ControllerParams, Exponential, IntOptions,
                              OptionSpec, RevertStack, SpanStat, Trigger,
                              WaitGroup)
from cilium_tpu.utils.metrics import Registry
from cilium_tpu.utils.option import (DAEMON_OPTION_LIBRARY, OPTION_ENABLED,
                                     parse_option_value)


# ---------------------------------------------------------------- controller

def test_controller_runs_and_retries():
    calls = []
    fail_until = 2

    def do():
        calls.append(1)
        if len(calls) <= fail_until:
            raise RuntimeError("transient")

    mgr = ControllerManager()
    ctrl = mgr.update_controller(
        "test", ControllerParams(do_func=do, error_retry_base=0.01))
    deadline = time.time() + 5
    while len(calls) < 3 and time.time() < deadline:
        time.sleep(0.01)
    assert len(calls) >= 3
    assert ctrl.status.failure_count == 2
    assert ctrl.status.success_count >= 1
    assert ctrl.status.consecutive_failures == 0
    mgr.remove_all()


def test_controller_update_replaces_func():
    a_calls, b_calls = [], []
    mgr = ControllerManager()
    mgr.update_controller("x", ControllerParams(
        do_func=lambda: a_calls.append(1)))
    time.sleep(0.05)
    # same name => replace, not a second controller
    mgr.update_controller("x", ControllerParams(
        do_func=lambda: b_calls.append(1)))
    deadline = time.time() + 5
    while not b_calls and time.time() < deadline:
        time.sleep(0.01)
    assert b_calls
    status = mgr.status_model()
    assert [s["name"] for s in status] == ["x"]
    assert mgr.remove_controller("x")
    assert not mgr.remove_controller("x")


def test_controller_interval():
    calls = []
    mgr = ControllerManager()
    mgr.update_controller("tick", ControllerParams(
        do_func=lambda: calls.append(time.time()), run_interval=0.02))
    deadline = time.time() + 5
    while len(calls) < 3 and time.time() < deadline:
        time.sleep(0.01)
    assert len(calls) >= 3
    mgr.remove_all()


# ------------------------------------------------------------------- trigger

def test_trigger_folds_bursts():
    runs = []
    got = threading.Event()

    def fn(reasons):
        runs.append(reasons)
        got.set()

    t = Trigger(fn, min_interval=0.05, name="t")
    for i in range(10):
        t.trigger(f"r{i % 2}")
    assert got.wait(5)
    time.sleep(0.15)
    t.shutdown()
    # 10 triggers folded into far fewer runs; reasons deduplicated
    assert 1 <= len(runs) <= 3
    assert set(runs[0]) <= {"r0", "r1"}


def test_trigger_min_interval_spacing():
    stamps = []
    t = Trigger(lambda r: stamps.append(time.time()), min_interval=0.05)
    t.trigger()
    time.sleep(0.01)
    t.trigger()
    deadline = time.time() + 5
    while len(stamps) < 2 and time.time() < deadline:
        time.sleep(0.005)
    t.shutdown()
    assert len(stamps) >= 2
    assert stamps[1] - stamps[0] >= 0.04


# ---------------------------------------------------------------- completion

def test_completion_waitgroup():
    wg = WaitGroup()
    c1 = wg.add_completion()
    c2 = wg.add_completion()
    assert not wg.wait(timeout=0.05)
    c1.complete()
    assert not wg.wait(timeout=0.05)
    c2.complete()
    assert wg.wait(timeout=1)
    assert c1.completed and c2.completed


def test_completion_callback_once():
    hits = []
    c = Completion(on_complete=lambda: hits.append(1))
    c.complete()
    c.complete()
    assert hits == [1]


# -------------------------------------------------------------------- revert

def test_revert_stack_lifo():
    order = []
    st = RevertStack()
    st.push(lambda: order.append("a"))
    st.push(lambda: order.append("b"))
    st.revert()
    assert order == ["b", "a"]
    st.revert()  # stack cleared
    assert order == ["b", "a"]


def test_revert_stack_error_propagates_but_all_run():
    order = []
    st = RevertStack()
    st.push(lambda: order.append("a"))

    def boom():
        order.append("boom")
        raise ValueError("x")

    st.push(boom)
    with pytest.raises(ValueError):
        st.revert()
    assert order == ["boom", "a"]


# ------------------------------------------------------------------- backoff

def test_backoff_growth_and_cap():
    b = Exponential(min_s=0.1, max_s=0.5, factor=2.0)
    assert b.duration(0) == pytest.approx(0.1)
    assert b.duration(1) == pytest.approx(0.2)
    assert b.duration(10) == pytest.approx(0.5)  # capped
    ev = threading.Event()
    ev.set()
    assert b.wait(ev) is False  # pre-set event interrupts immediately


# ------------------------------------------------------------------- options

def test_options_enable_pulls_requires():
    opts = IntOptions()
    changed = []
    n = opts.apply_validated({"ConntrackAccounting": 1},
                             changed=lambda k, v: changed.append((k, v)))
    # enabling accounting enables Conntrack too
    assert n == 2
    assert opts.is_enabled("Conntrack")
    assert opts.is_enabled("ConntrackAccounting")
    assert ("Conntrack", 1) in changed


def test_options_disable_cascades_dependents():
    opts = IntOptions()
    opts.apply_validated({"ConntrackAccounting": 1})
    n = opts.apply_validated({"Conntrack": 0})
    assert n == 2  # both disabled
    assert not opts.is_enabled("ConntrackAccounting")


def test_options_unknown_and_immutable_rejected():
    opts = IntOptions()
    with pytest.raises(KeyError):
        opts.apply_validated({"NoSuchOption": 1})
    lib = dict(DAEMON_OPTION_LIBRARY)
    lib["Frozen"] = OptionSpec("Frozen", immutable=True)
    opts2 = IntOptions(library=lib)
    with pytest.raises(ValueError):
        opts2.apply_validated({"Frozen": 1})


def test_options_fork_is_independent():
    parent = IntOptions(defaults={"Policy": 1})
    child = parent.fork()
    child.apply_validated({"Policy": 0})
    assert parent.is_enabled("Policy")
    assert not child.is_enabled("Policy")


def test_parse_option_value():
    assert parse_option_value("true") == OPTION_ENABLED
    assert parse_option_value("Disabled") == 0
    assert parse_option_value(True) == 1
    with pytest.raises(ValueError):
        parse_option_value("maybe")


# ------------------------------------------------------------------ spanstat

def test_spanstat_success_failure_split():
    s = SpanStat()
    with s:
        pass
    try:
        with s:
            raise RuntimeError()
    except RuntimeError:
        pass
    assert s.num_success == 1
    assert s.num_failure == 1
    assert s.seconds() >= 0


# ------------------------------------------------------------------- metrics

def test_metrics_counter_gauge_histogram_exposition():
    reg = Registry(namespace="t")
    c = reg.counter("hits", "hits")
    c.inc()
    c.inc(2, labels={"reason": "policy"})
    g = reg.gauge("eps")
    g.set(4)
    g.dec()
    h = reg.histogram("lat", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    text = reg.expose_text()
    assert 't_hits{reason="policy"} 2.0' in text
    assert "t_eps 3.0" in text
    assert 't_lat_bucket{le="0.1"} 1' in text
    assert 't_lat_bucket{le="+Inf"} 2' in text
    assert "# TYPE t_hits counter" in text
    assert c.value(labels={"reason": "policy"}) == 2.0
    # same-name registration returns the existing metric
    assert reg.counter("hits") is c


# --------------------------------------------- review-regression coverage

def test_options_cascade_respects_guards():
    # enabling A must fail atomically if a cascaded dep is immutable
    lib = {
        "A": OptionSpec("A", requires=["B"]),
        "B": OptionSpec("B", immutable=True),
    }
    opts = IntOptions(library=lib)
    with pytest.raises(ValueError):
        opts.apply_validated({"A": 1})
    assert not opts.is_enabled("A") and not opts.is_enabled("B")
    # unknown dep in the requires list also fails before mutation
    lib2 = {"A": OptionSpec("A", requires=["Missing"])}
    opts2 = IntOptions(library=lib2)
    with pytest.raises(KeyError):
        opts2.apply_validated({"A": 1})
    assert not opts2.is_enabled("A")


def test_completion_concurrent_complete_fires_once():
    hits = []
    c = Completion(on_complete=lambda: hits.append(1))
    threads = [threading.Thread(target=c.complete) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert hits == [1]


def test_metrics_label_escaping():
    reg = Registry(namespace="esc")
    c = reg.counter("drops")
    c.inc(labels={"reason": 'CT "invalid"\nstate\\x'})
    text = reg.expose_text()
    assert 'reason="CT \\"invalid\\"\\nstate\\\\x"' in text


def test_metrics_kind_collision_raises():
    reg = Registry(namespace="k")
    reg.counter("hits")
    with pytest.raises(ValueError):
        reg.gauge("hits")


def test_probe_features():
    """Runtime capability probing (bpf/run_probes.sh analog)."""
    from cilium_tpu.utils.platform import probe_features
    f = probe_features()
    assert f["backend"] == "cpu"          # conftest pins CPU
    assert f["on_accelerator"] is False
    assert f["device_count"] == 8          # virtual mesh
    assert isinstance(f["pallas"], bool)
    assert "hash" in f["verdict_engines"]
    assert "bucket2choice" in f["verdict_engines"]
    if f["native_fastpath"]:
        assert "host-cache" in f["verdict_engines"]


def test_status_reports_features():
    from cilium_tpu.daemon import Daemon
    from cilium_tpu.utils.option import DaemonConfig
    d = Daemon(config=DaemonConfig())
    try:
        st = d.status()
        assert st["features"]["backend"] == "cpu"
        assert "verdict_engines" in st["features"]
    finally:
        d.shutdown()
