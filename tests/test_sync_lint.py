"""Hot-dispatch sync-point lint: the async serving path can't silently
regress.

The latency-tier PR's whole win is that the steady-state dispatch loop
never blocks on device compute — the one permitted synchronization is
the ticket-completion transfer in the serving dispatcher's "complete"
stage (a flagged blocking boundary, always one batch behind the launch
front).  This lint holds that line structurally: any device-sync
construct (``block_until_ready``, ``np.asarray`` on an in-flight
array, ``jax.device_get``) inside the hot dispatch modules — or inside
the engine's hot functions — must carry an explicit
``# sync-ok: <reason>`` marker naming why that boundary is allowed.
Adding an unmarked sync is a test failure, not a review nit.
"""

import ast
import os
import re

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# whole modules on the steady-state dispatch path
HOT_MODULES = (
    "cilium_tpu/datapath/serving.py",
    "cilium_tpu/datapath/supervisor.py",
    "cilium_tpu/verdict_service.py",
    "cilium_tpu/l7/parser.py",
    # the sharded dataplane's routing/fan-out path: splitting and
    # reassembly must never sync — each shard's lane owns its one
    # flagged "complete" boundary
    "cilium_tpu/parallel/mesh.py",
    "cilium_tpu/parallel/specs.py",
    "cilium_tpu/parallel/sharded.py",
    # the dispatch-floor packing: manifest build, group concat, and
    # delta write-through all sit under the engine lock on the
    # control->dataplane boundary — a sync here stalls every dispatch
    "cilium_tpu/parallel/packing.py",
    # the observability plane rides the dispatch path (SLO hooks per
    # resolved ticket, flight-recorder emitters on mode transitions,
    # the federated observer's drain): pure host arithmetic, zero
    # sync markers by construction
    "cilium_tpu/observability/slo.py",
    "cilium_tpu/observability/events.py",
    "cilium_tpu/hubble/federation.py",
    # the L7 fast-verdict program compiler: table lowering is
    # control-plane, but its payload-encode helpers run per serving
    # submission — zero sync markers by construction
    "cilium_tpu/l7/fast.py",
    # the inline threat-scoring plane: the fused stage + model math
    # run inside the jitted steps, the oracle/trainer are host-side
    # parity/fit code — zero sync markers by construction in all four
    "cilium_tpu/threat/stage.py",
    "cilium_tpu/threat/model.py",
    "cilium_tpu/threat/oracle.py",
    "cilium_tpu/threat/trainer.py",
    # the device traffic-analytics plane: the fused sketch stage runs
    # inside the jitted steps, the oracle is host-side parity code,
    # the decoder reads only quiesced host snapshots — zero sync
    # markers by construction in all three
    "cilium_tpu/analytics/stage.py",
    "cilium_tpu/analytics/oracle.py",
    "cilium_tpu/analytics/decode.py",
)

# the engine is hot only in its dispatch functions — table loading,
# map dumps and replay are control-plane and sync freely
ENGINE_MODULE = "cilium_tpu/datapath/engine.py"
ENGINE_HOT_FUNCS = {"process", "process6", "process_packed",
                    "_flow_step_variant", "_timestamp",
                    "_payload_in", "_dispatch_locked",
                    "_account_dispatch", "_flush_verdict_counts",
                    "serving"}

# device-sync constructs; (?<!j) keeps jnp.asarray (an async H2D used
# by the pack stage) out of the np.asarray net
SYNC_RE = re.compile(
    r"block_until_ready|(?<!j)np\.asarray\(|jax\.device_get"
    r"|\.addressable_data\(|device_put_sharded")

MARKER_RE = re.compile(r"#\s*sync-ok:\s*\S")


def _module_lines(relpath):
    with open(os.path.join(REPO, relpath)) as f:
        return f.read().splitlines()


def _engine_hot_lines():
    """(lineno, text) for every line inside the engine's hot
    functions, located via the AST so refactors can't silently move a
    function out of lint coverage."""
    lines = _module_lines(ENGINE_MODULE)
    tree = ast.parse("\n".join(lines))
    found = set()
    out = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name in ENGINE_HOT_FUNCS:
            found.add(node.name)
            for ln in range(node.lineno, node.end_lineno + 1):
                out.append((ln, lines[ln - 1]))
    missing = ENGINE_HOT_FUNCS - found
    assert not missing, \
        f"engine hot functions renamed/removed — update lint: {missing}"
    return out


def _all_hot_lines():
    for rel in HOT_MODULES:
        for i, line in enumerate(_module_lines(rel), start=1):
            yield rel, i, line
    for ln, line in _engine_hot_lines():
        yield ENGINE_MODULE, ln, line


def test_no_unflagged_sync_in_hot_dispatch_modules():
    violations = [
        f"{rel}:{ln}: {line.strip()}"
        for rel, ln, line in _all_hot_lines()
        if SYNC_RE.search(line) and "sync-ok" not in line]
    assert not violations, (
        "device synchronization inside the hot dispatch path without "
        "an explicit '# sync-ok: <reason>' marker:\n"
        + "\n".join(violations))


def test_sync_ok_markers_carry_reasons():
    bare = [
        f"{rel}:{ln}: {line.strip()}"
        for rel, ln, line in _all_hot_lines()
        if "sync-ok" in line and not MARKER_RE.search(line)]
    assert not bare, (
        "'sync-ok' markers must name their reason "
        "('# sync-ok: <why this boundary is allowed>'):\n"
        + "\n".join(bare))


def test_whitelisted_boundaries_stay_bounded():
    """The whitelist itself is pinned: the serving path keeps exactly
    its known sync boundaries (the ticket-completion transfer pair in
    serving.py, the is_ready-gated verdict-count drain in the engine).
    Growing this list is a deliberate, reviewed act."""
    marked = [(rel, ln) for rel, ln, line in _all_hot_lines()
              if "sync-ok" in line and SYNC_RE.search(line)]
    by_module = {}
    for rel, _ln in marked:
        by_module[rel] = by_module.get(rel, 0) + 1
    assert by_module == {
        "cilium_tpu/datapath/serving.py": 2,
        ENGINE_MODULE: 1,
    }, by_module
