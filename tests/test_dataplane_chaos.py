"""Device-lane chaos: the survivable-serving tier
(datapath/supervisor.py) under injected faults.

The acceptance journey, end to end: injected device fault -> breaker
opens -> established-CT flows still ALLOW via the host fail-static
oracle (no blanket deny) -> injected heal -> table rebuild +
drift-audit gate -> breaker closes, dataplane_recoveries_total
increments, status() returns to ok.  Plus the watchdog (a hung
``complete`` sync is a fault), fault classification (fatal trips the
breaker immediately), oracle parity (fail-static answers bit-exact
with what the device would decide for new flows), the configured
degraded-mode policies, a failing recovery gate keeping the lane
degraded, and the disabled-supervision path dispatching the
byte-identical pre-change program.
"""

import json
import time

import numpy as np
import pytest

from bench import build_config1
from cilium_tpu.datapath.engine import Datapath, make_full_batch
from cilium_tpu.datapath.serving import VerdictDispatcher
from cilium_tpu.datapath.supervisor import (DeviceSupervisor,
                                            classify_fault)
from cilium_tpu.utils.faultinject import (DeviceFaultInjector,
                                          DeviceLaneFault)
from cilium_tpu.utils.metrics import (DATAPLANE_DEVICE_FAULTS,
                                      DATAPLANE_FAIL_STATIC,
                                      DATAPLANE_RECOVERIES)

N_ENDPOINTS = 8


def _load_dp(**kw):
    states, prefixes = build_config1(n_rules=40,
                                     n_endpoints=N_ENDPOINTS)
    dp = Datapath(ct_slots=1 << 12)
    dp.telemetry_enabled = False
    if kw:
        dp.configure_supervision(**kw)
    dp.load_policy(states, revision=1, ipcache_prefixes=prefixes)
    return dp, prefixes


def _supervised(dp, **kw):
    kw.setdefault("watchdog_s", 5.0)
    kw.setdefault("failure_threshold", 2)
    kw.setdefault("reset_s", 0.05)
    sup = DeviceSupervisor(dp, **kw)
    disp = VerdictDispatcher(dp, supervisor=sup,
                             lane=f"chaos-{id(sup) & 0xFFFF:x}")
    inj = DeviceFaultInjector()
    sup.install_fault_hook(inj)
    return disp, sup, inj


_SPORT = [20000]


def _chunk(rng, n, prefixes=None, hit_frac=0.5):
    """SoA record chunk; with ``prefixes``, the first ``hit_frac`` of
    daddrs land inside installed ipcache prefixes so a share of the
    batch genuinely ALLOWs (and creates CT entries)."""
    base = _SPORT[0]
    _SPORT[0] += n
    daddr = rng.integers(0, 1 << 32, n, dtype=np.uint32)
    if prefixes:
        cidrs = list(prefixes)
        for j in range(int(n * hit_frac)):
            a = cidrs[j % len(cidrs)].split("/")[0].split(".")
            daddr[j] = (int(a[0]) << 24) | (int(a[1]) << 16) | \
                (int(a[2]) << 8) | 7
    return {
        "endpoint": rng.integers(0, N_ENDPOINTS, n).astype(np.int32),
        "saddr": rng.integers(0, 1 << 32, n,
                              dtype=np.uint32).view(np.int32),
        "daddr": daddr.view(np.int32),
        "sport": ((base + np.arange(n)) % 64000 + 1024
                  ).astype(np.int32),
        "dport": rng.integers(1, 65536, n).astype(np.int32),
        "proto": np.full(n, 6, np.int32),
        "direction": np.ones(n, np.int32),
        "tcp_flags": np.full(n, 0x02, np.int32),
        "is_fragment": np.zeros(n, np.int32),
        "length": np.full(n, 256, np.int32),
    }


def _cp(c):
    return {k: v.copy() for k, v in c.items()}


def _submit(disp, c, n=None):
    n = n if n is not None else len(c["sport"])
    t = disp.submit_records(_cp(c), n)
    v, i = t.result(timeout=120)
    return t, np.asarray(v), np.asarray(i)


# ------------------------------------------------ fault classification

def test_fault_classification():
    assert classify_fault(DeviceLaneFault(fatal=True)) == "fatal"
    assert classify_fault(DeviceLaneFault()) == "transient"
    assert classify_fault(OSError("link down")) == "transient"
    # engine preconditions are caller errors, never device faults
    assert classify_fault(
        RuntimeError("no policy loaded")) == "caller"

    class XlaRuntimeError(RuntimeError):
        pass

    assert classify_fault(
        XlaRuntimeError("INTERNAL: device halted")) == "fatal"
    assert classify_fault(
        XlaRuntimeError("RESOURCE_EXHAUSTED: oom")) == "transient"


# ------------------------------------------- fail-static established

def test_transient_faults_open_breaker_and_established_flows_survive():
    """The core fail-static property: after the breaker opens, flows
    with live CT entries keep their verdicts — no blanket deny."""
    dp, prefixes = _load_dp()
    disp, sup, inj = _supervised(dp)
    rng = np.random.default_rng(5)
    try:
        c1 = _chunk(rng, 64, prefixes)
        t, v1, i1 = _submit(disp, c1)
        assert t.error is None
        allowed = v1 >= 0
        assert allowed.any(), "config must allow a share of c1"
        sup.oracle.refresh()
        assert sup.oracle.stats()["ct-entries"] > 0

        static_before = DATAPLANE_FAIL_STATIC.total()
        faults_before = DATAPLANE_DEVICE_FAULTS.total()
        inj.fail_launch(times=2)          # threshold is 2
        for _ in range(2):
            t, v, _i = _submit(disp, c1)
            assert t.error is None        # served static, not denied
        assert sup.mode == "degraded"
        assert sup.breaker.state == "open"
        assert DATAPLANE_DEVICE_FAULTS.total() == faults_before + 2

        # established flows keep their verdicts while degraded
        t, vs, _is = _submit(disp, c1)
        assert t.error is None
        np.testing.assert_array_equal(vs[allowed],
                                      np.maximum(v1[allowed], 0))
        assert DATAPLANE_FAIL_STATIC.total() > static_before
        assert disp.stats()["static-batches"] >= 1
    finally:
        disp.close()


def test_fatal_fault_trips_breaker_immediately():
    dp, prefixes = _load_dp()
    disp, sup, inj = _supervised(dp, failure_threshold=5)
    rng = np.random.default_rng(7)
    try:
        _submit(disp, _chunk(rng, 32, prefixes))  # settle + compile
        sup.oracle.refresh()
        inj.fail_launch(times=1, fatal=True)
        t, _v, _i = _submit(disp, _chunk(rng, 32, prefixes))
        assert t.error is None
        assert sup.mode == "degraded"     # one fatal fault sufficed
        assert sup.faults.get("fatal") == 1
    finally:
        disp.close()


# -------------------------------------------------- watchdog deadline

def test_hung_finalize_is_a_fault_via_watchdog():
    """A finalize that outlives the watchdog deadline — the hung
    ``complete`` sync of a wedged device path — must resolve the batch
    fail-static within ~the watchdog budget, not hang the lane."""
    dp, prefixes = _load_dp()
    disp, sup, inj = _supervised(dp, watchdog_s=0.2,
                                 failure_threshold=3)
    rng = np.random.default_rng(9)
    try:
        _submit(disp, _chunk(rng, 32, prefixes))
        sup.oracle.refresh()
        inj.hang_finalize(seconds=1.5)
        t0 = time.perf_counter()
        t, _v, _i = _submit(disp, _chunk(rng, 32, prefixes))
        took = time.perf_counter() - t0
        assert t.error is None
        assert took < 1.2, f"watchdog did not fire ({took:.2f}s)"
        assert sup.faults.get("hung") == 1
        assert sup.mode == "degraded"     # hung = trip immediately
        # the abandoned worker eventually finishes; the lane recovers
        time.sleep(1.6)
        t, _v, _i = _submit(disp, _chunk(rng, 32, prefixes))
        assert sup.mode == "ok" and sup.recoveries == 1
    finally:
        disp.close()


# -------------------------------------------- oracle verdict parity

@pytest.mark.parametrize("seed", [11, 13])
def test_fail_static_new_flow_parity_with_device(seed):
    """Degraded-mode 'oracle' answers for NEW flows must be bit-exact
    with what the device path would decide (verdict AND identity) —
    fail-static enforces last-known-good policy, it does not invent a
    different one."""
    dp, prefixes = _load_dp()
    disp, sup, inj = _supervised(dp)
    oracle_dp, _ = _load_dp()
    rng = np.random.default_rng(seed)
    try:
        _submit(disp, _chunk(rng, 32, prefixes))
        sup.oracle.refresh()
        fresh = _chunk(rng, 200, prefixes)   # never seen by either dp
        pkt = make_full_batch(**fresh)
        dv, _e, di, _n = oracle_dp.process(pkt)
        dv, di = np.asarray(dv), np.asarray(di)

        inj.fail_launch(times=2)
        for _ in range(2):
            _submit(disp, _chunk(rng, 16, prefixes))
        assert sup.mode == "degraded"
        t, sv, si = _submit(disp, fresh)
        assert t.error is None
        np.testing.assert_array_equal(sv, dv)
        np.testing.assert_array_equal(si, di)
    finally:
        disp.close()


@pytest.mark.parametrize("policy,expect", [("deny", -1), ("allow", 0)])
def test_degraded_new_flow_policy_knob(policy, expect):
    dp, prefixes = _load_dp()
    disp, sup, inj = _supervised(dp, new_flow_policy=policy)
    rng = np.random.default_rng(17)
    try:
        _submit(disp, _chunk(rng, 16, prefixes))
        sup.oracle.refresh()
        inj.fail_launch(times=2)
        for _ in range(2):
            _submit(disp, _chunk(rng, 16, prefixes))
        assert sup.mode == "degraded"
        t, v, _i = _submit(disp, _chunk(rng, 32, prefixes))
        assert t.error is None
        assert (v == expect).all(), v
    finally:
        disp.close()


# ------------------------------------------------------- recovery

def test_recovery_gate_failure_keeps_lane_degraded():
    """A half-open probe may NOT resume on a failing drift gate: the
    breaker re-opens (doubling cadence) until the gate passes."""
    gate_results = [False, False, True]
    gate_calls = []

    def gate():
        gate_calls.append(time.monotonic())
        return gate_results[min(len(gate_calls) - 1,
                                len(gate_results) - 1)]

    dp, prefixes = _load_dp()
    disp, sup, inj = _supervised(dp, recovery_gate=gate,
                                 reset_s=0.05)
    rng = np.random.default_rng(19)
    try:
        _submit(disp, _chunk(rng, 16, prefixes))
        sup.oracle.refresh()
        inj.fail_launch(times=2)
        for _ in range(2):
            _submit(disp, _chunk(rng, 16, prefixes))
        assert sup.mode == "degraded"
        deadline = time.monotonic() + 20.0
        while sup.mode != "ok" and time.monotonic() < deadline:
            time.sleep(0.05)
            _submit(disp, _chunk(rng, 8, prefixes))
        assert sup.mode == "ok"
        assert len(gate_calls) == 3      # two failed probes first
        assert sup.recoveries == 1
    finally:
        disp.close()


def test_transient_then_heal_script_recovers_with_probe_cadence():
    """The scripted transient-then-heal choreography: every launch
    faults for a while, the breaker holds the lane static between
    probes, and the first healthy probe (gated) closes it."""
    dp, prefixes = _load_dp()
    disp, sup, inj = _supervised(dp, reset_s=0.05)
    rng = np.random.default_rng(23)
    try:
        _submit(disp, _chunk(rng, 16, prefixes))
        sup.oracle.refresh()
        rec_before = DATAPLANE_RECOVERIES.total()
        inj.script([("launch", "raise", False)] * 4)
        deadline = time.monotonic() + 20.0
        while (sup.mode != "ok" or inj.armed) and \
                time.monotonic() < deadline:
            t, _v, _i = _submit(disp, _chunk(rng, 8, prefixes))
            assert t.error is None       # never fail-closed mid-chaos
            time.sleep(0.02)
        assert sup.mode == "ok"
        assert DATAPLANE_RECOVERIES.total() > rec_before
        assert inj.injected == 4
    finally:
        disp.close()


def test_recovery_rebuilds_device_tables_from_host_of_record():
    """While degraded, scribble over the LIVE device policy tensors
    (what a real device loss looks like); recovery must rebuild from
    the host-of-record, pass the drift gate, and serve correct
    verdicts again."""
    dp, prefixes = _load_dp()
    disp, sup, inj = _supervised(dp)
    rng = np.random.default_rng(29)
    try:
        c = _chunk(rng, 64, prefixes)
        t, v1, _i = _submit(disp, c)
        sup.oracle.refresh()
        inj.fail_launch(times=2)
        for _ in range(2):
            _submit(disp, _chunk(rng, 8, prefixes))
        assert sup.mode == "degraded"
        # corrupt the device-resident policy stack — BOTH the raw
        # tensors and the packed dispatch buffers the jitted step
        # actually reads (host-of-record, i.e. the compiled
        # artifacts, stays intact)
        import jax.numpy as jnp
        bad = dp._tables.datapath._replace(
            key_meta=jnp.zeros_like(dp._tables.datapath.key_meta))
        dp._tables = dp._tables._replace(datapath=bad)
        dp._tbufs4 = tuple(jnp.zeros_like(b) for b in dp._tbufs4)
        time.sleep(0.1)
        fresh = _chunk(rng, 64, prefixes)
        t, v2, _i = _submit(disp, fresh)
        assert sup.mode == "ok" and sup.recoveries == 1
        # the rebuilt tables answer like a pristine engine
        oracle_dp, _ = _load_dp()
        pkt = make_full_batch(**fresh)
        dv = np.asarray(oracle_dp.process(pkt)[0])
        np.testing.assert_array_equal(v2, dv)
    finally:
        disp.close()


# ------------------------------------- disabled supervision contract

def test_supervision_disabled_is_the_pre_change_path():
    """enable_supervision=off: no supervisor on the lane, launch
    failures keep the PR 7 fail-closed deny contract, and the
    compiled device program is byte-identical to the supervised
    engine's (supervision is host-side only)."""
    import jax.numpy as jnp
    dp_off, prefixes = _load_dp(enabled=False)
    dp_on, _ = _load_dp()
    disp_off = dp_off.serving()
    disp_on = dp_on.serving()
    try:
        assert disp_off.supervisor is None
        assert disp_on.supervisor is not None
        packed = jnp.zeros((10, 16), jnp.int32)
        lowered = [dp._step_packed.lower(
            *dp._lower_args_packed(packed)).as_text()
            for dp in (dp_off, dp_on)]
        assert lowered[0] == lowered[1]
        # same records, same verdicts through both lanes
        rng = np.random.default_rng(31)
        c = _chunk(rng, 48, prefixes)
        t_off, v_off, i_off = _submit(disp_off, c)
        t_on, v_on, i_on = _submit(disp_on, c)
        assert t_off.error is None and t_on.error is None
        np.testing.assert_array_equal(v_off, v_on)
        np.testing.assert_array_equal(i_off, i_on)
    finally:
        disp_off.close()
        disp_on.close()


# --------------------------------------------- daemon-level journey

def test_daemon_journey_fault_failstatic_recovery_status():
    """The acceptance journey on a LIVE daemon: device fault ->
    breaker opens -> established flows still ALLOW fail-static ->
    status() fails loudly -> heal -> rebuild + drift-audit gate ->
    recovery counted, status back to ok."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from cilium_tpu.daemon import Daemon
    from cilium_tpu.policy.jsonio import rules_from_json
    from cilium_tpu.utils.option import DaemonConfig

    cfg = DaemonConfig(state_dir="", drift_audit_interval_s=0,
                       ct_checkpoint_interval_s=0,
                       supervisor_reset_s=0.05,
                       supervisor_watchdog_s=5.0,
                       supervisor_failure_threshold=2)
    d = Daemon(config=cfg)
    try:
        d.endpoint_create(1, ipv4="10.200.0.10", labels=["k8s:id=web"])
        d.endpoint_create(2, ipv4="10.200.0.11", labels=["k8s:id=db"])
        rules = rules_from_json(json.dumps([{
            "endpointSelector": {"matchLabels": {"id": "db"}},
            "ingress": [{
                "fromEndpoints": [{"matchLabels": {"id": "web"}}],
                "toPorts": [{"ports": [{"port": "5432",
                                        "protocol": "TCP"}]}]}],
            "labels": ["k8s:policy=t"]}]))
        rev = d.policy_add(rules)
        assert d.wait_for_policy_revision(rev, timeout=60)
        assert d.status()["dataplane"]["status"] == "ok"

        disp = d.datapath.serving()
        sup = disp.supervisor
        slot = d.endpoints.lookup(2).table_slot
        web_ip = (10 << 24) | (200 << 16) | 10
        db_ip = (10 << 24) | (200 << 16) | 11

        def records(n, dport, sport0):
            return {
                "endpoint": np.full(n, slot, np.int32),
                "saddr": np.full(n, web_ip, np.uint32).view(np.int32),
                "daddr": np.full(n, db_ip, np.uint32).view(np.int32),
                "sport": (sport0 + np.arange(n)).astype(np.int32),
                "dport": np.full(n, dport, np.int32),
                "proto": np.full(n, 6, np.int32),
                "direction": np.zeros(n, np.int32),   # ingress to db
                "tcp_flags": np.full(n, 0x02, np.int32),
                "is_fragment": np.zeros(n, np.int32),
                "length": np.full(n, 256, np.int32)}

        allowed = records(8, 5432, 40000)
        t, v, i = _submit(disp, allowed)
        assert t.error is None and (v == 0).all()   # flows establish
        sup.oracle.refresh()
        assert sup.oracle.stats()["ct-entries"] >= 8

        rec_before = DATAPLANE_RECOVERIES.total()
        inj = DeviceFaultInjector()
        sup.install_fault_hook(inj)
        inj.fail_launch(times=2)
        for _ in range(2):
            _submit(disp, records(8, 5432, 40000))
        # breaker open: status fails loudly
        st = d.status()["dataplane"]
        assert st["mode"] == "degraded"
        assert st["status"].startswith("DEGRADED")

        # established flows keep ALLOW (no blanket deny) ...
        t, vs, _ = _submit(disp, allowed)
        assert t.error is None and (vs == 0).all()
        # ... while a disallowed NEW flow stays denied
        t, vd, _ = _submit(disp, records(8, 80, 41000))
        assert t.error is None and (vd < 0).all()

        # heal -> probe -> rebuild + drift-audit gate -> recovered
        inj.heal()
        time.sleep(0.1)
        t, v2, _ = _submit(disp, allowed)
        assert t.error is None and (v2 == 0).all()
        assert sup.mode == "ok"
        assert DATAPLANE_RECOVERIES.total() > rec_before
        st = d.status()["dataplane"]
        assert st["mode"] == "ok" and st["status"] == "ok"
        # the gate really ran the drift audit
        assert d.drift_report() is not None
        assert d.drift_report()["status"] in ("ok", "idle")
    finally:
        d.shutdown()
