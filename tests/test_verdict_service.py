"""Verdict service: remote header batches -> TPU verdicts over TCP.

The daemon->TPU verdict-service RPC hop (SURVEY §5/§2.8/§7): clients
ship PKT_HEADER_DTYPE record batches; the service coalesces them
through the C++ SPSC ring into device-sized dispatches and answers per
frame, in order.
"""

import struct
import threading

import numpy as np
import pytest

from cilium_tpu.daemon import Daemon
from cilium_tpu.daemon.daemon import DaemonConfig
from cilium_tpu.labels import LabelArray
from cilium_tpu.native import PKT_HEADER_DTYPE
from cilium_tpu.policy.api import (EndpointSelector, IngressRule,
                                   PortProtocol, PortRule, Rule)
from cilium_tpu.verdict_service import (VerdictClient, VerdictService,
                                        VerdictServiceError)


@pytest.fixture()
def wired_daemon():
    d = Daemon(config=DaemonConfig())
    web = d.endpoint_create(1, ipv4="10.200.3.1",
                            labels=["k8s:app=web"])
    db = d.endpoint_create(2, ipv4="10.200.3.2", labels=["k8s:app=db"])
    d.policy_add([Rule(
        endpoint_selector=EndpointSelector.parse("app=db"),
        ingress=[IngressRule(
            from_endpoints=[EndpointSelector.parse("app=web")],
            to_ports=[PortRule(ports=[
                PortProtocol(port="5432", protocol="TCP")])])])])
    assert d.wait_for_quiesce(30)
    yield d, web, db
    d.shutdown()


def _records(db_slot, web_ip_u32, db_ip_u32, sports, dports):
    n = len(sports)
    recs = np.zeros(n, PKT_HEADER_DTYPE)
    recs["endpoint"] = db_slot
    recs["saddr"] = web_ip_u32
    recs["daddr"] = db_ip_u32
    recs["sport"] = sports
    recs["dport"] = dports
    recs["proto"] = 6
    recs["direction"] = 0
    recs["tcp_flags"] = 0x02
    recs["length"] = 100
    return recs


def _ip_u32(ip):
    from cilium_tpu.compiler.lpm import ipv4_to_u32
    return ipv4_to_u32(ip)


def test_remote_batch_verdicts_match_policy(wired_daemon):
    d, web, db = wired_daemon
    svc = VerdictService(d.datapath).start()
    try:
        client = VerdictClient("127.0.0.1", svc.port)
        recs = _records(db.table_slot, _ip_u32(web.ipv4),
                        _ip_u32(db.ipv4),
                        sports=[41000, 41001, 41002],
                        dports=[5432, 80, 22])
        v, ids = client.classify(recs)
        assert v[0] >= 0          # allowed port
        assert v[1] < 0 and v[2] < 0
        assert (ids == web.security_identity).all()
        client.close()
    finally:
        svc.shutdown()


def test_many_small_frames_coalesce_and_answer_in_order(wired_daemon):
    d, web, db = wired_daemon
    svc = VerdictService(d.datapath).start()
    try:
        client = VerdictClient("127.0.0.1", svc.port)
        for k in range(30):
            port = 5432 if k % 2 == 0 else 81
            recs = _records(db.table_slot, _ip_u32(web.ipv4),
                            _ip_u32(db.ipv4),
                            sports=[42000 + k], dports=[port])
            v, ids = client.classify(recs)
            assert (v[0] >= 0) == (k % 2 == 0), (k, v)
        assert svc.frames_served == 30
        client.close()
    finally:
        svc.shutdown()


def test_frame_larger_than_max_batch_splits_and_reassembles(
        wired_daemon):
    d, web, db = wired_daemon
    # tiny device batches force the split/reassembly path
    svc = VerdictService(d.datapath, max_batch=32).start()
    try:
        client = VerdictClient("127.0.0.1", svc.port)
        n = 200
        dports = np.where(np.arange(n) % 3 == 0, 5432, 9999)
        recs = _records(db.table_slot, _ip_u32(web.ipv4),
                        _ip_u32(db.ipv4),
                        sports=43000 + np.arange(n), dports=dports)
        v, ids = client.classify(recs)
        assert len(v) == n
        want_allow = np.arange(n) % 3 == 0
        assert ((v >= 0) == want_allow).all()
        assert svc.batches_dispatched > 1  # really split
        client.close()
    finally:
        svc.shutdown()


def test_pipelined_clients_from_threads(wired_daemon):
    d, web, db = wired_daemon
    svc = VerdictService(d.datapath).start()
    errors = []

    def worker(base):
        try:
            client = VerdictClient("127.0.0.1", svc.port)
            for k in range(10):
                recs = _records(db.table_slot, _ip_u32(web.ipv4),
                                _ip_u32(db.ipv4),
                                sports=[base + k], dports=[5432])
                v, _ = client.classify(recs)
                if not v[0] >= 0:
                    errors.append((base, k, int(v[0])))
            client.close()
        except Exception as e:  # noqa: BLE001
            errors.append(repr(e))

    try:
        threads = [threading.Thread(target=worker, args=(50000 + i * 100,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors
    finally:
        svc.shutdown()


def test_protocol_error_drops_connection(wired_daemon):
    d, _web, _db = wired_daemon
    svc = VerdictService(d.datapath).start()
    try:
        import socket as _socket
        s = _socket.create_connection(("127.0.0.1", svc.port),
                                      timeout=5)
        s.sendall(struct.pack(">III", 0xBAD, 1, 4))
        s.settimeout(5)
        assert s.recv(1) == b""  # server closed on us
        s.close()
    finally:
        svc.shutdown()


def test_dispatcher_failure_closes_connection_not_hangs():
    """Review regression: a classify error (e.g. no policy loaded)
    must drop the connection so the client fails fast instead of
    hanging until its socket timeout."""
    from cilium_tpu.datapath.engine import Datapath
    bare = Datapath(ct_slots=1 << 10)  # no policy loaded -> raises
    svc = VerdictService(bare).start()
    try:
        client = VerdictClient("127.0.0.1", svc.port, timeout=10)
        recs = np.zeros(4, PKT_HEADER_DTYPE)
        recs["proto"] = 6
        with pytest.raises(VerdictServiceError):
            client.classify(recs)
        client.close()
    finally:
        svc.shutdown()


def test_agent_verdict_port_flag_parses():
    """--verdict-port parse contract (service construction itself is
    covered by the tests above; cmd_agent's loop is not runnable
    in-process)."""
    from cilium_tpu.cli import build_parser
    args = build_parser().parse_args(["agent", "--verdict-port", "0"])
    assert args.verdict_port == 0
    args = build_parser().parse_args(
        ["agent", "--verdict-port", "19999"])
    assert args.verdict_port == 19999


def test_peer_auth_challenge_response(wired_daemon):
    """Round-4 weak #6: the cross-node deployment story needs peer
    authentication.  With a shared secret, connecting is a
    challenge-response HMAC handshake: the right secret classifies,
    the wrong one is rejected before any frame is served, and a
    non-loopback bind without a secret refuses to start at all."""
    d, web, db = wired_daemon
    svc = VerdictService(d.datapath, secret=b"s3cret").start()
    try:
        client = VerdictClient("127.0.0.1", svc.port, secret=b"s3cret")
        recs = _records(db.table_slot, _ip_u32(web.ipv4),
                        _ip_u32(db.ipv4), sports=[45100],
                        dports=[5432])
        v, _ = client.classify(recs)
        assert int(v[0]) >= 0
        client.close()
        # wrong secret: handshake rejected, no frames served
        with pytest.raises(VerdictServiceError):
            VerdictClient("127.0.0.1", svc.port, secret=b"wrong")
        # no secret: the client never answers the challenge; its first
        # classify cannot succeed (server closes on garbage/eof)
        bare = VerdictClient("127.0.0.1", svc.port, timeout=5)
        with pytest.raises(VerdictServiceError):
            bare.classify(recs)
        bare.close()
        assert svc.frames_served == 1
    finally:
        svc.shutdown()
    # fail closed: non-loopback bind without a secret refuses
    with pytest.raises(ValueError):
        VerdictService(d.datapath, host="0.0.0.0")


def test_client_empty_batch_short_circuits(wired_daemon):
    d, _web, _db = wired_daemon
    svc = VerdictService(d.datapath).start()
    try:
        client = VerdictClient("127.0.0.1", svc.port)
        v, ids = client.classify(np.zeros(0, PKT_HEADER_DTYPE))
        assert len(v) == 0 and len(ids) == 0
        # the connection survives for real work afterwards
        recs = np.zeros(1, PKT_HEADER_DTYPE)
        recs["proto"] = 6
        v, _ = client.classify(recs)
        assert len(v) == 1
        client.close()
    finally:
        svc.shutdown()
