"""Inline per-packet ML threat scoring (cilium_tpu/threat/): the
Taurus-style anomaly verdict plane fused into the jitted pipelines.

- **Score parity** — device scores/arms/verdict overrides replayed
  against the numpy oracle bit-exactly, across seeds and batches, v4
  AND v6, with flows + provenance fused (the full-pipeline shape).
- **Shadow is bit-exact** — scoring fused in shadow mode never changes
  a verdict/event/tier vs the pre-threat engine on identical traffic.
- **Enforce arms** — drop / redirect / token-bucket rate-limit,
  DROP_THREAT events + TIER_THREAT_* provenance.
- **Hot-swap** — weight pushes and threshold/mode flips are leaf
  writes through the delta-apply path: zero repacks, no re-jit.
- **Disabled path** — enable->disable lowers the byte-identical
  pre-threat program (lowered-HLO-asserted).
- **Sharded isolation** — per-shard token-bucket/window state.
- **Supervisor degraded** — fail-static serves POLICY verdicts: a
  broken device lane (and with it the model) can never deny traffic
  the policy allows.
- **Live-daemon journey** — train from the flow plane -> hot-swap
  push -> status/REST -> flight-recorder events on mode flips.
"""

import numpy as np
import pytest

from cilium_tpu.datapath.engine import Datapath, make_full_batch6
from cilium_tpu.datapath.events import (DROP_THREAT, TIER_NAMES,
                                        TIER_THREAT_DROP,
                                        TIER_THREAT_RATELIMIT,
                                        TIER_THREAT_REDIRECT)
from cilium_tpu.datapath.pipeline import PACKED_FIELDS
from cilium_tpu.datapath.verdict import VERDICT_DROP, VERDICT_DROP_THREAT
from cilium_tpu.policy.mapstate import (EGRESS, INGRESS, PolicyKey,
                                        PolicyMapState,
                                        PolicyMapStateEntry)
from cilium_tpu.threat import (NUM_FEATURES, ThreatConfig, ThreatModel,
                               ThreatTrainer, default_model)
from cilium_tpu.threat.model import linear_model
from cilium_tpu.threat.oracle import (flow_snapshot_index,
                                      oracle_threat_step)
from cilium_tpu.threat.stage import STATE_COLS, unpack_threat_out

HTTP_ID, DNS_ID = 777, 888
WORLD = 2
EP_IDENTITY = 1234
BUCKETS = 256


def _policy():
    st = PolicyMapState()
    st[PolicyKey(identity=HTTP_ID, dest_port=80, nexthdr=6,
                 direction=INGRESS)] = PolicyMapStateEntry()
    st[PolicyKey(identity=DNS_ID, dest_port=53, nexthdr=17,
                 direction=EGRESS)] = PolicyMapStateEntry()
    return st


ENFORCE_CFG = ThreatConfig(mode="enforce", drop_score=235,
                           ratelimit_score=150, rate_per_s=2.0,
                           burst=4)


def _engine(config=None, flows=True, provenance=True, threat=True,
            ct_slots=1 << 10, model=None):
    dp = Datapath(ct_slots=ct_slots)
    dp.telemetry_enabled = False
    if provenance:
        dp.enable_provenance()
    if flows:
        dp.enable_flow_aggregation(slots=1 << 8, claim_every=1)
    if threat:
        dp.enable_threat(model or default_model(
            config or ThreatConfig()), buckets=BUCKETS, window_s=8)
    dp.load_policy([_policy()], revision=1, ipcache_prefixes={
        "10.0.0.0/8": HTTP_ID, "20.0.0.0/8": DNS_ID})
    dp.set_endpoint_identity(0, EP_IDENTITY)
    return dp


def _traffic(rng, n, sport0):
    """Mixed batch: allowed HTTP ingress (10/8 -> 777), allowed DNS
    egress (daddr 20/8 -> 888), and WORLD-sourced denied rows."""
    kind = rng.integers(0, 3, n)           # 0 http, 1 dns, 2 denied
    is_http = kind == 0
    is_dns = kind == 1
    saddr = np.where(is_http, (10 << 24) | 5, (50 << 24) | 9) \
        .astype(np.uint32)
    daddr = np.where(is_dns, (20 << 24) | 9, (10 << 24) | 8) \
        .astype(np.uint32)
    recs = {
        "endpoint": np.zeros(n, np.int32),
        "saddr": saddr.view(np.int32),
        "daddr": daddr.view(np.int32),
        "sport": (sport0 + np.arange(n)).astype(np.int32),
        "dport": np.where(is_http, 80,
                          np.where(is_dns, 53,
                                   rng.integers(1, 65536, n))
                          ).astype(np.int32),
        "proto": np.where(is_dns, 17, 6).astype(np.int32),
        "direction": np.where(is_http, 0, 1).astype(np.int32),
        "tcp_flags": np.where(rng.random(n) < 0.5, 0x02, 0x10)
        .astype(np.int32),
        "length": rng.integers(60, 1500, n).astype(np.int32),
        "is_fragment": np.zeros(n, np.int32),
    }
    stage = np.empty((len(PACKED_FIELDS), n), np.int32)
    for i, f in enumerate(PACKED_FIELDS):
        stage[i] = recs[f]
    return stage, recs


def _identities(recs):
    """Host ipcache twin: resolved peer identity per row."""
    sa = recs["saddr"].view(np.uint32)
    da = recs["daddr"].view(np.uint32)
    peer = np.where(recs["direction"] == 0, sa, da)
    ident = np.full(peer.shape[0], WORLD, np.int32)
    ident[(peer >> 24) == 10] = HTTP_ID
    ident[(peer >> 24) == 20] = DNS_ID
    return ident


def _policy_verdict(ident, recs):
    """Host policy twin of the two installed rules."""
    ok = ((ident == HTTP_ID) & (recs["dport"] == 80) &
          (recs["proto"] == 6) & (recs["direction"] == 0)) | \
         ((ident == DNS_ID) & (recs["dport"] == 53) &
          (recs["proto"] == 17) & (recs["direction"] == 1))
    return np.where(ok, 0, VERDICT_DROP).astype(np.int32)


def _established_from_ct(dp, recs):
    """Pre-batch established view from the live CT dump (forward
    tuples only; test traffic never sends replies)."""
    live = {(e["saddr"], e["daddr"], e["sport"], e["dport"],
             e["proto"]) for e in dp.map_dump("ct", max_entries=1 << 14)}
    sa = recs["saddr"].view(np.uint32)
    da = recs["daddr"].view(np.uint32)
    return np.array([
        (int(sa[i]), int(da[i]), int(recs["sport"][i]),
         int(recs["dport"][i]), int(recs["proto"][i])) in live
        for i in range(sa.shape[0])], bool)


def _oracle_flow_ids(ident, recs):
    """pipeline._flow_identities twin: (src, dst) flow-key identities
    for endpoint slot 0 (own identity EP_IDENTITY)."""
    egress = recs["direction"] == 1
    src = np.where(egress, EP_IDENTITY, ident)
    dst = np.where(egress, ident, EP_IDENTITY)
    return src, dst


# ------------------------------------------------------ score parity

@pytest.mark.parametrize("seed", [11, 12, 13])
def test_score_parity_vs_oracle_v4(seed):
    """Device scores, bands, fired masks, verdict overrides AND the
    evolving token-bucket/window state replay bit-exactly against the
    numpy oracle over multiple batches — flows + provenance fused,
    enforce mode with live drop + rate-limit arms."""
    rng = np.random.default_rng(seed)
    model = default_model(ENFORCE_CFG)
    dp = _engine(model=model)
    mirror = np.zeros((BUCKETS + 1, STATE_COLS), np.int32)
    now = 1000
    sport0 = 20000
    for batch in range(3):
        n = 96
        stage, recs = _traffic(rng, n, sport0)
        if batch == 2:
            # re-hit batch 0's tuples: established flows + flow-table
            # history exercise the CT/flow features
            stage[3] = 20000 + np.arange(n)
            recs["sport"] = stage[3].copy()
        sport0 += n
        ident = _identities(recs)
        pre_verdict = np.where(_established_from_ct(dp, recs), 0,
                               _policy_verdict(ident, recs))
        established = _established_from_ct(dp, recs)
        pre_verdict = np.where(established, 0,
                               _policy_verdict(ident, recs))
        flow_index = flow_snapshot_index(dp.flow_snapshot(1 << 14))
        fsrc, fdst = _oracle_flow_ids(ident, recs)
        exp_v, exp_out, exp_score, exp_band, exp_drop, exp_redir, \
            exp_rl = oracle_threat_step(
                mirror, model, pre_verdict, identity=ident,
                dport=recs["dport"], proto=recs["proto"],
                tcp_flags=recs["tcp_flags"], length=recs["length"],
                is_fragment=recs["is_fragment"],
                established=established,
                saddr_w=recs["saddr"], daddr_w=recs["daddr"],
                sport=recs["sport"], flow_src=fsrc, flow_dst=fdst,
                now=now, window_s=8, flow_index=flow_index)
        v, e, got_ident, _nat = dp.process_packed(stage, now=now)
        v = np.asarray(v)
        np.testing.assert_array_equal(np.asarray(got_ident), ident)
        np.testing.assert_array_equal(
            np.asarray(dp.last_threat), exp_out,
            err_msg=f"threat_out diverged (batch {batch})")
        np.testing.assert_array_equal(
            v, exp_v, err_msg=f"verdict diverged (batch {batch})")
        # the device state buffer matches the oracle mirror exactly
        np.testing.assert_array_equal(
            np.asarray(dp.threat_state.state), mirror,
            err_msg=f"threat state diverged (batch {batch})")
        # provenance tiers for fired rows
        tiers = np.asarray(dp.last_provenance.tier)
        assert (tiers[exp_rl] == TIER_THREAT_RATELIMIT).all()
        assert (tiers[exp_drop & ~exp_rl] == TIER_THREAT_DROP).all()
        now += 3


def test_score_parity_vs_oracle_v6():
    """The v6 twin scores through the shared model; tuple hashes use
    the CT address folds."""
    from cilium_tpu.datapath.pipeline import fold6
    import jax.numpy as jnp
    model = default_model(ENFORCE_CFG)
    dp = Datapath(ct_slots=1 << 8)
    dp.telemetry_enabled = False
    dp.enable_provenance()
    dp.enable_threat(model, buckets=BUCKETS, window_s=8)
    dp.load_policy([_policy()], revision=1)
    dp.load_ipcache6({"fd00::/16": HTTP_ID})
    dp.set_endpoint_identity(0, EP_IDENTITY)
    n = 24
    pkt = make_full_batch6(
        endpoint=[0] * n, saddr=["fd00::5"] * n, daddr=["fd00::9"] * n,
        sport=[30000 + i for i in range(n)], dport=[80] * n,
        proto=[6] * n, direction=[0] * n)
    mirror = np.zeros((BUCKETS + 1, STATE_COLS), np.int32)
    ident = np.full(n, HTTP_ID, np.int32)
    saddr_w = np.asarray(fold6(pkt.saddr))
    daddr_w = np.asarray(fold6(pkt.daddr))
    exp_v, exp_out, *_rest = oracle_threat_step(
        mirror, model, np.zeros(n, np.int32), identity=ident,
        dport=np.asarray(pkt.dport), proto=np.asarray(pkt.proto),
        tcp_flags=np.asarray(pkt.tcp_flags),
        length=np.asarray(pkt.length),
        is_fragment=np.asarray(pkt.is_fragment),
        established=np.zeros(n, bool), saddr_w=saddr_w,
        daddr_w=daddr_w, sport=np.asarray(pkt.sport),
        flow_src=ident, flow_dst=np.full(n, EP_IDENTITY, np.int32),
        now=500, window_s=8, flow_index=None)
    v, e, _i, _nat = dp.process6(pkt, now=500)
    np.testing.assert_array_equal(np.asarray(dp.last_threat), exp_out)
    np.testing.assert_array_equal(np.asarray(v), exp_v)
    np.testing.assert_array_equal(np.asarray(dp.threat_state.state),
                                  mirror)


# ------------------------------------------------- shadow bit-exact

def test_shadow_mode_never_changes_verdicts():
    """Shadow-mode scoring over identical traffic produces bit-exact
    verdicts/events/tiers vs a threat-free twin — even with a model
    that would drop everything in enforce mode."""
    hot = linear_model(np.full(NUM_FEATURES, 2000.0), bias=255,
                       config=ThreatConfig(mode="shadow",
                                           drop_score=1))
    a = _engine(model=hot)
    b = _engine(threat=False)
    rng = np.random.default_rng(99)
    now = 2000
    for batch in range(3):
        stage, _recs = _traffic(np.random.default_rng(99 + batch), 64,
                                40000 + 64 * batch)
        va, ea, ia, _ = a.process_packed(stage, now=now)
        vb, eb, ib, _ = b.process_packed(stage.copy(), now=now)
        np.testing.assert_array_equal(np.asarray(va), np.asarray(vb))
        np.testing.assert_array_equal(np.asarray(ea), np.asarray(eb))
        np.testing.assert_array_equal(
            np.asarray(a.last_provenance.tier),
            np.asarray(b.last_provenance.tier))
        # the scorer RAN: max-weight model saturates the score lane,
        # and eligible (policy-allowed) rows classify into the drop
        # band — without firing
        score, band, fired = unpack_threat_out(a.last_threat)
        assert (score == 255).all()
        assert (band[np.asarray(vb) >= 0] == 3).all()
        assert not fired.any(), "shadow mode must never fire"
        now += 1


# ------------------------------------------------------ enforce arms

def test_enforce_drop_arm():
    dp = _engine(model=default_model(
        ThreatConfig(mode="enforce", drop_score=100)), flows=False)
    stage, recs = _traffic(np.random.default_rng(1), 32, 50000)
    v, e, _i, _n = dp.process_packed(stage, now=100)
    v, e = np.asarray(v), np.asarray(e)
    allowed = _policy_verdict(_identities(recs), recs) == 0
    assert allowed.any()
    # every policy-allowed row scores as a fresh SYN-ish flow over the
    # default model -> above the drop threshold -> DROP_THREAT
    score, _band, fired = unpack_threat_out(dp.last_threat)
    should = allowed & (score >= 100)
    assert should.any()
    assert (v[should] == VERDICT_DROP_THREAT).all()
    assert (e[should] == DROP_THREAT).all()
    # policy-denied rows keep their ORIGINAL drop (never re-tiered)
    assert (v[~allowed] == VERDICT_DROP).all()


def test_enforce_redirect_arm():
    dp = _engine(model=default_model(
        ThreatConfig(mode="enforce", redirect_score=100,
                     redirect_port=14999)), flows=False)
    stage, recs = _traffic(np.random.default_rng(2), 32, 51000)
    v, _e, _i, _n = dp.process_packed(stage, now=100)
    v = np.asarray(v)
    allowed = _policy_verdict(_identities(recs), recs) == 0
    score, band, fired = unpack_threat_out(dp.last_threat)
    should = allowed & (score >= 100)
    assert should.any()
    assert (v[should] == 14999).all()
    assert (np.asarray(dp.last_provenance.tier)[should]
            == TIER_THREAT_REDIRECT).all()


def test_enforce_ratelimit_token_bucket():
    """Rate-limit band: the identity's bucket admits its burst, then
    dry-bucket packets drop probabilistically keyed on score."""
    dp = _engine(model=default_model(
        ThreatConfig(mode="enforce", ratelimit_score=100,
                     rate_per_s=0.0, burst=2)), flows=False)
    dropped = 0
    passed = 0
    for batch in range(4):
        stage, recs = _traffic(np.random.default_rng(3), 64,
                               52000 + 64 * batch)
        stage[3] = 52000 + 64 * batch + np.arange(64)  # fresh flows
        v = np.asarray(dp.process_packed(stage, now=100 + batch)[0])
        allowed = _policy_verdict(_identities(recs), recs) == 0
        dropped += int((v[allowed] == VERDICT_DROP_THREAT).sum())
        passed += int((v[allowed] == 0).sum())
    assert dropped > 0, "dry bucket must drop"
    assert passed > 0, "rate-limit is probabilistic, not a blackhole"
    tiers = np.asarray(dp.last_provenance.tier)
    v = np.asarray(v)
    assert (tiers[v == VERDICT_DROP_THREAT]
            == TIER_THREAT_RATELIMIT).all()


# ------------------------------------------- hot swap / config flips

def test_weight_hot_swap_zero_repacks():
    """A trained same-geometry model pushes through the delta-apply
    leaf-write path: zero full repacks, no re-jit, and the very next
    batch scores under the new weights."""
    dp = _engine(flows=False)
    stage, _recs = _traffic(np.random.default_rng(4), 16, 53000)
    dp.process_packed(stage, now=100)
    s0, _b, _f = unpack_threat_out(dp.last_threat)
    packs = dp.pack_stats()["full-packs"]
    writes = dp.pack_stats()["leaf-writes"]
    zero = linear_model(np.zeros(NUM_FEATURES),
                        config=ThreatConfig(generation=2))
    assert dp.apply_threat_weights(zero) is True
    stats = dp.pack_stats()
    assert stats["full-packs"] == packs, "weight push repacked"
    assert stats["leaf-writes"] > writes
    stage[3] = 54000 + np.arange(16)
    dp.process_packed(stage, now=101)
    s1, _b, _f = unpack_threat_out(dp.last_threat)
    assert (s1 == 0).all() and (s0 > 0).any()
    assert dp.threat_report()["config"]["generation"] == 2


def test_config_flip_is_a_leaf_write():
    dp = _engine(flows=False)
    packs = dp.pack_stats()["full-packs"]
    dp.set_threat_config(ThreatConfig(mode="enforce", drop_score=50))
    assert dp.pack_stats()["full-packs"] == packs
    stage, recs = _traffic(np.random.default_rng(5), 16, 55000)
    v = np.asarray(dp.process_packed(stage, now=100)[0])
    allowed = _policy_verdict(_identities(recs), recs) == 0
    assert (v[allowed] == VERDICT_DROP_THREAT).any()


# ---------------------------------------------------- disabled path

def test_disabled_path_is_byte_identical():
    import jax.numpy as jnp
    base = _engine(threat=False, flows=False)
    tog = _engine(flows=False)
    stage = jnp.asarray(np.zeros((10, 16), np.int32))
    en_txt = tog._step_packed.lower(
        *tog._lower_args_packed(stage)).as_text()
    tog.disable_threat()
    base_txt = base._step_packed.lower(
        *base._lower_args_packed(stage)).as_text()
    tog_txt = tog._step_packed.lower(
        *tog._lower_args_packed(stage)).as_text()
    assert tog_txt == base_txt
    assert en_txt != base_txt
    assert base.dispatch_leaf_counts() == tog.dispatch_leaf_counts()


# ------------------------------------------------ sharded isolation

def test_sharded_token_bucket_isolation():
    """Each shard owns its OWN ThreatState: one shard's window counts
    and token debt never leak into a sibling's buffer (shard-local,
    the CT precedent)."""
    from cilium_tpu.parallel.sharded import ShardedDatapath
    states = [_policy() for _ in range(4)]
    p = ShardedDatapath(n_shards=2, ct_slots=1 << 8)
    p.telemetry_enabled = False
    p.enable_threat(default_model(
        ThreatConfig(mode="enforce", ratelimit_score=100,
                     rate_per_s=0.0, burst=1)), buckets=BUCKETS)
    p.load_policy(states, revision=1,
                  ipcache_prefixes={"10.0.0.0/8": HTTP_ID})
    n = 32
    recs = {
        "endpoint": np.zeros(n, np.int32),   # global ep 0 -> shard 0
        "saddr": np.full(n, (10 << 24) | 5, np.uint32).view(np.int32),
        "daddr": np.full(n, (10 << 24) | 9, np.uint32).view(np.int32),
        "sport": (56000 + np.arange(n)).astype(np.int32),
        "dport": np.full(n, 80, np.int32),
        "proto": np.full(n, 6, np.int32),
        "direction": np.zeros(n, np.int32),
        "tcp_flags": np.full(n, 0x02, np.int32),
        "length": np.full(n, 100, np.int32),
        "is_fragment": np.zeros(n, np.int32),
    }
    v, _i = p.classify_records(
        {k: v.copy() for k, v in recs.items()}, n)
    st0 = np.asarray(p.shards[0].threat_state.state)
    st1 = np.asarray(p.shards[1].threat_state.state)
    assert st0.any(), "shard 0 must have scored its traffic"
    assert not st1.any(), "shard 1's state must be untouched"
    # now shard 1 (odd endpoints): its state moves, shard 0's frozen
    recs["endpoint"] = np.ones(n, np.int32)
    recs["sport"] = (57000 + np.arange(n)).astype(np.int32)
    p.classify_records(recs, n)
    st0b = np.asarray(p.shards[0].threat_state.state)
    st1b = np.asarray(p.shards[1].threat_state.state)
    assert st1b.any()
    np.testing.assert_array_equal(st0, st0b)
    p.serving().close()


# --------------------------------------- supervisor fail-static

def test_supervisor_degraded_fail_static_to_policy_verdict():
    """A tripped device lane serves POLICY verdicts from the host
    oracle — threat enforcement (which would drop everything here)
    cannot deny traffic the policy allows while degraded."""
    from cilium_tpu.datapath.serving import VerdictDispatcher
    from cilium_tpu.datapath.supervisor import DeviceSupervisor
    from cilium_tpu.utils.faultinject import (DeviceFaultInjector,
                                              DeviceLaneFault)
    dp = _engine(model=default_model(
        ThreatConfig(mode="enforce", drop_score=1)), flows=False)
    sup = DeviceSupervisor(dp, watchdog_s=5.0, failure_threshold=1,
                           reset_s=60.0)
    disp = VerdictDispatcher(dp, supervisor=sup, lane="threat-chaos")
    inj = DeviceFaultInjector()
    sup.install_fault_hook(inj)
    n = 16
    recs = {
        "endpoint": np.zeros(n, np.int32),
        "saddr": np.full(n, (10 << 24) | 5, np.uint32).view(np.int32),
        "daddr": np.full(n, (10 << 24) | 9, np.uint32).view(np.int32),
        "sport": (58000 + np.arange(n)).astype(np.int32),
        "dport": np.full(n, 80, np.int32),
        "proto": np.full(n, 6, np.int32),
        "direction": np.zeros(n, np.int32),
        "tcp_flags": np.full(n, 0x02, np.int32),
        "length": np.full(n, 100, np.int32),
        "is_fragment": np.zeros(n, np.int32),
    }
    # on-device: the enforce model drops the allowed traffic
    t = disp.submit_records({k: v.copy() for k, v in recs.items()}, n)
    v, _i = t.result(timeout=60)
    assert (v == VERDICT_DROP_THREAT).all()
    # trip the lane: fail-static answers the POLICY verdict (allow)
    inj.fail_launch(times=4, fatal=True)
    recs["sport"] = (59000 + np.arange(n)).astype(np.int32)
    t2 = disp.submit_records(recs, n)
    v2, _i2 = t2.result(timeout=60)
    assert sup.mode == "degraded"
    assert (v2 == 0).all(), \
        "degraded lane must fail static to the policy verdict"
    disp.close()


# ------------------------------------------------ live-daemon journey

def test_live_daemon_threat_journey(tmp_path):
    """train -> push -> status -> flight recorder: the full operator
    loop on a live agent with the threat plane enabled in shadow."""
    from cilium_tpu.daemon import Daemon
    from cilium_tpu.daemon.rest import APIServer
    from cilium_tpu.utils.option import DaemonConfig
    from cilium_tpu.observability.events import recorder
    from cilium_tpu.utils.metrics import THREAT_VERDICTS
    d = Daemon(config=DaemonConfig(
        state_dir="", drift_audit_interval_s=0,
        ct_checkpoint_interval_s=0, enable_threat=True,
        enable_provenance=True))
    server = APIServer(d).start()
    try:
        assert d.status()["threat"]["mode"] == "shadow"
        # traffic through the fused pipeline populates the flow plane
        stage, recs = _traffic(np.random.default_rng(7), 64, 60000)
        v, e, ident, _nat = d.datapath.process_packed(stage, now=100)
        prov = d.datapath.last_provenance
        base_scored = THREAT_VERDICTS.value(
            labels={"outcome": "scored"})
        d.monitor.ingest_batch(
            np.asarray(e), recs["endpoint"], np.asarray(ident),
            recs["dport"], recs["proto"], recs["length"],
            tiers=np.asarray(prov.tier),
            match_slots=np.asarray(prov.match_slot),
            threat_out=np.asarray(d.datapath.last_threat))
        assert THREAT_VERDICTS.value(
            labels={"outcome": "scored"}) - base_scored == 64
        # train from the aggregated flow plane + hot-swap push
        out = d.threat_train(max_flows=1024)
        assert out["training"]["flows"] > 0
        assert out["push"]["hot-swap"] is True
        gen = out["push"]["generation"]
        assert gen >= 2
        # flight recorder carries the push event
        types = [ev.type for ev in recorder.events(limit=0)]
        assert "threat-model-push" in types
        # REST: status + config flip to enforce -> mode-flip event
        from cilium_tpu.cli import Client
        c = Client(f"http://127.0.0.1:{server.port}")
        got = c.get("/threat")
        assert got["model"]["config"]["generation"] == gen
        c.post("/threat/config", {"mode": "enforce",
                                  "drop-score": 250})
        st = d.status()["threat"]
        assert st["mode"] == "enforce"
        assert st["status"].startswith("ENFORCING")
        flips = [ev for ev in recorder.events(limit=0)
                 if ev.type == "threat-mode"]
        assert flips and flips[-1].attrs["mode"] == "enforce"
        # back to shadow: verdicts bit-exact again
        c.post("/threat/config", {"mode": "shadow"})
        assert d.status()["threat"]["mode"] == "shadow"
    finally:
        server.shutdown()
        d.shutdown()


# --------------------------------------------------- grammar / misc

def test_tier_grammar_and_event_mapping():
    from cilium_tpu.hubble.filter import parse_tier
    from cilium_tpu.hubble.flow import (VERDICT_DROPPED,
                                        verdict_of_event)
    assert parse_tier("threat-drop") == "threat-drop"
    assert parse_tier(TIER_THREAT_RATELIMIT) == "threat-ratelimit"
    assert TIER_NAMES[TIER_THREAT_REDIRECT] == "threat-redirect"
    assert verdict_of_event(DROP_THREAT) == VERDICT_DROPPED


def test_trainer_separates_drop_flows():
    """The numpy trainer learns to score drop-event flows above
    allowed flows, and the quantized model preserves the ordering."""
    rng = np.random.default_rng(42)
    flows = []
    for i in range(200):
        bad = i % 2 == 0
        flows.append({
            "src-identity": WORLD if bad else HTTP_ID,
            "dst-identity": EP_IDENTITY,
            "dport": int(rng.integers(1, 65536)) if bad else 80,
            "proto": 6,
            "event": -130 if bad else 0,
            "packets": int(rng.integers(1, 4)) if bad
            else int(rng.integers(50, 500)),
            "bytes": int(rng.integers(40, 200)) if bad
            else int(rng.integers(5000, 50000)),
            "last-seen": 100})
    trainer = ThreatTrainer()
    model = trainer.fit(flows, now=100)
    assert trainer.last_report["train-accuracy"] >= 0.9
    from cilium_tpu.threat.trainer import features_from_flow
    bad_scores = model.score(np.stack(
        [features_from_flow(f, 100) for f in flows[0::2]]))
    good_scores = model.score(np.stack(
        [features_from_flow(f, 100) for f in flows[1::2]]))
    assert bad_scores.mean() > good_scores.mean() + 20
