"""Control-plane outage survivability under injected chaos.

The control-plane twin of tests/test_dataplane_chaos.py: the kvstore
(etcd) and the apiserver are driven through blackholes, partitions,
flaps, and lease expiry by ``ControlPlaneFaultInjector``, and the
outage layer (kvstore/outage.py + kvstore/journal.py + the identity
fallback in kvstore/identity_allocator.py) must absorb them:

- sustained kvstore failure flips ``kvstore_mode`` to degraded;
  identities/ipcache/nodes pin last-known-good with a growing
  staleness age; the dataplane keeps serving bit-exact verdicts;
- an endpoint created during the outage gets a node-local ephemeral
  identity (local scope, bit 24) and correct verdicts;
- mutations journal (per-key-coalesced, bounded) and replay on
  reconnect, followed by the relist-and-diff repair of locally owned
  lease-backed keys;
- local identities are promoted to cluster scope on reconnect via the
  incremental delta-apply path — regeneration bounded by the
  actually-diverged endpoint set, established flows keep forwarding;
- the disabled path is behavior-identical to an unwrapped backend.
"""

import json
import time

import numpy as np
import pytest

from cilium_tpu.daemon import Daemon
from cilium_tpu.identity import (LOCAL_SCOPE_IDENTITY_BASE,
                                 is_local_scope_identity)
from cilium_tpu.kvstore.etcd import EtcdBackend
from cilium_tpu.kvstore.identity_allocator import (
    DistributedIdentityAllocator, FallbackIdentityAllocator)
from cilium_tpu.kvstore.journal import WriteJournal
from cilium_tpu.kvstore.memory import InMemoryBackend
from cilium_tpu.kvstore.mini_etcd import MiniEtcd
from cilium_tpu.kvstore.outage import KVStoreDegradedError, OutageGuard
from cilium_tpu.labels import Labels, parse_label
from cilium_tpu.policy.jsonio import rules_from_json
from cilium_tpu.policy.mapstate import PolicyMapState
from cilium_tpu.utils.faultinject import (ControlPlaneFaultInjector,
                                          FaultProxy)
from cilium_tpu.utils.metrics import (KVSTORE_RECONCILE,
                                      POLICY_REGENERATION_COUNT)
from cilium_tpu.utils.option import DaemonConfig

WEB_IP, DB_IP, TMP_IP = "10.200.0.10", "10.200.0.11", "10.200.0.12"


def _wait_for(cond, timeout=30.0, interval=0.05, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


def _labels(*items):
    return Labels.from_labels(parse_label(i) for i in items)


# ------------------------------------------------------- unit: journal

def test_write_journal_coalesces_and_bounds():
    j = WriteJournal(max_entries=3)
    j.record("set", "a", b"1")
    j.record("set", "a", b"2")
    assert j.depth() == 1 and j.stats()["coalesced"] == 1
    j.record("delete", "a")
    # the delete replaced the pending set — replay ends with a delete
    assert j.depth() == 1 and j.snapshot()[0].op == "delete"
    # delete_prefix subsumes pending mutations under the prefix
    j.record("set", "p/x", b"1")
    j.record("set", "p/y", b"2")
    j.record("delete_prefix", "p/")
    assert j.depth() == 2
    ops = [e.op for e in j.snapshot()]
    assert ops == ["delete", "delete_prefix"]
    # bound: oldest evicted with accounting
    j.record("set", "b", b"1")
    j.record("set", "c", b"1")
    assert j.depth() == 3
    assert j.stats()["dropped"] == 1
    # replay order is by sequence
    seqs = [e.seq for e in j.snapshot()]
    assert seqs == sorted(seqs)
    # a live write supersedes the pending entry
    j.discard_key("c")
    assert all(e.key != "c" for e in j.snapshot())


# --------------------------------------------------- unit: outage guard

class _FlakyBackend(InMemoryBackend):
    """In-memory backend with a failure switch."""

    def __init__(self):
        super().__init__()
        self.fail = False

    def _gate(self):
        if self.fail:
            raise OSError("injected kvstore failure")

    def get(self, key):
        self._gate()
        return super().get(key)

    def list_prefix(self, prefix):
        self._gate()
        return super().list_prefix(prefix)

    def set(self, key, value, lease=False):
        self._gate()
        return super().set(key, value, lease)

    def delete(self, key):
        self._gate()
        return super().delete(key)

    def lock_path(self, path, timeout=30.0):
        self._gate()
        return super().lock_path(path, timeout)


def test_outage_guard_degrades_journals_and_reconciles():
    inner = _FlakyBackend()
    guard = OutageGuard(inner, degrade=True, failure_threshold=2,
                        probe_interval=0.05)
    guard.track_prefix("t/")
    guard.set("t/pre", b"v0", lease=True)
    assert guard.mode == "ok" and guard.staleness() == 0.0

    inner.fail = True
    # mutations during the failing window journal instead of raising
    guard.set("t/k", b"v1", lease=True)
    guard.set("t/k", b"v2", lease=True)   # coalesces
    assert guard.mode == "degraded"
    assert guard.journal.depth() == 1
    # reads and locks fail FAST while degraded (no per-op timeouts)
    t0 = time.monotonic()
    with pytest.raises((KVStoreDegradedError, OSError)):
        guard.get("t/pre")
    assert time.monotonic() - t0 < 0.5
    with pytest.raises((KVStoreDegradedError, OSError)):
        guard.lock_path("t/lock")
    # a non-lease CAS create must not be faked
    with pytest.raises((KVStoreDegradedError, OSError)):
        guard.create_only("t/master", b"x")
    assert guard.staleness() > 0.0
    rep = guard.report()
    assert rep["mode"] == "degraded" and rep["journal-depth"] == 1

    # the server "reaps" a lease-backed key behind our back (lease
    # expiry during the outage) — the reconcile must re-assert it
    InMemoryBackend.delete(inner, "t/pre")

    inner.fail = False
    reconciles = KVSTORE_RECONCILE.value(labels={"result": "ok"})
    time.sleep(0.1)
    event = guard.tick()
    assert event.get("reconciled") is True
    assert guard.mode == "ok"
    assert inner.get("t/k") == b"v2"       # journal replayed
    assert inner.get("t/pre") == b"v0"     # lease-grace repair
    report = event["report"]
    assert report["replayed"] == 1 and report["repaired"] == 1
    assert KVSTORE_RECONCILE.value(labels={"result": "ok"}) > reconciles
    assert guard.journal.depth() == 0


def test_outage_guard_disabled_is_passthrough():
    """degrade=False: bookkeeping only — every op delegates with
    identical semantics and exceptions (the pre-change behavior)."""
    inner = _FlakyBackend()
    guard = OutageGuard(inner, degrade=False)
    guard.set("k", b"v")
    assert guard.get("k") == b"v"
    inner.fail = True
    with pytest.raises(OSError):
        guard.set("k", b"v2")      # raises, never journals
    with pytest.raises(OSError):
        guard.get("k")
    assert guard.journal.depth() == 0
    assert guard.mode == "ok"      # mode never flips when disabled
    # ... but the status bookkeeping still tracks the failure
    assert guard.staleness() > 0.0
    assert guard.report()["consecutive-failures"] >= 2
    inner.fail = False
    assert guard.get("k") == b"v"
    assert guard.staleness() == 0.0
    assert guard.tick() == {}      # tick is inert when disabled


# ------------------------------------- unit: identity fallback/adoption

def test_fallback_allocator_local_scope_and_adoption():
    backend = InMemoryBackend()
    guard = OutageGuard(backend, degrade=True, failure_threshold=1,
                        probe_interval=0.05)
    dist = DistributedIdentityAllocator(guard, node="n1")
    fb = FallbackIdentityAllocator(dist, guard=guard)
    try:
        # healthy: plain distributed allocation
        web, is_new = fb.allocate(_labels("k8s:id=web"))
        assert is_new and not is_local_scope_identity(web.id)

        # force degraded
        guard._note_failure()
        assert guard.mode == "degraded"

        # labels the cluster already bound: ADOPT the cached ID
        again, _ = fb.allocate(_labels("k8s:id=web"))
        assert again.id == web.id
        # release the extra ref (delete journals while degraded)
        fb.release(again)

        # genuinely new labels: node-local ephemeral identity
        tmp, is_new = fb.allocate(_labels("k8s:id=tmp"))
        assert is_new and is_local_scope_identity(tmp.id)
        assert tmp.id >= LOCAL_SCOPE_IDENTITY_BASE
        assert fb.local_count() == 1
        # same labels -> same local id, refcounted
        tmp2, is_new = fb.allocate(_labels("k8s:id=tmp"))
        assert not is_new and tmp2.id == tmp.id
        assert fb.lookup_by_id(tmp.id) == tmp
        assert fb.lookup_by_labels(_labels("k8s:id=tmp")).id == tmp.id
        assert any(i.id == tmp.id for i in fb.snapshot_identities())
        assert fb.release(tmp2) is False
        assert fb.release(tmp) is True
        assert fb.local_count() == 0
    finally:
        fb.close()


# ----------------------------------------- live-daemon outage journey

RULES_JSON = json.dumps([{
    "endpointSelector": {"matchLabels": {"id": "db"}},
    "ingress": [
        {"fromEndpoints": [{"matchLabels": {"id": "web"}}],
         "toPorts": [{"ports": [{"port": "5432", "protocol": "TCP"}]}]},
        {"fromEndpoints": [{"matchLabels": {"id": "tmp"}}],
         "toPorts": [{"ports": [{"port": "7000", "protocol": "TCP"}]}]},
    ],
    "labels": ["k8s:policy=cp-chaos"],
}])


@pytest.fixture()
def etcd_server():
    srv = MiniEtcd(reap_interval=0.1).start()
    yield srv
    srv.shutdown()


@pytest.fixture()
def injector(etcd_server):
    proxy = FaultProxy("127.0.0.1", etcd_server.port).start()
    inj = ControlPlaneFaultInjector(etcd=proxy,
                                    lease_expirer=etcd_server
                                    .expire_leases)
    yield inj
    inj.close()
    proxy.close()


def _ip_u32(dotted):
    a, b, c, d = (int(x) for x in dotted.split("."))
    return (a << 24) | (b << 16) | (c << 8) | d


def _recs(slot, n, dport, saddr, sport0, flags=0x02):
    return {"endpoint": np.full(n, slot, np.int32),
            "saddr": np.full(n, _ip_u32(saddr),
                             np.uint32).view(np.int32),
            "daddr": np.full(n, _ip_u32(DB_IP),
                             np.uint32).view(np.int32),
            "sport": (sport0 + np.arange(n)).astype(np.int32),
            "dport": np.full(n, dport, np.int32),
            "proto": np.full(n, 6, np.int32),
            "direction": np.zeros(n, np.int32),   # ingress to db
            "tcp_flags": np.full(n, flags, np.int32),
            "is_fragment": np.zeros(n, np.int32),
            "length": np.full(n, 256, np.int32)}


def _verdicts(disp, recs):
    t = disp.submit_records(recs, len(recs["sport"]))
    v, i = t.result(timeout=120)
    assert t.error is None
    return np.asarray(v), np.asarray(i)


def test_daemon_outage_journey(etcd_server, injector):
    """The acceptance journey: blackhole etcd mid-run -> degraded with
    growing staleness, dataplane bit-exact, outage endpoint on a
    local-scope identity with correct verdicts; reconnect -> journal
    replay + reconcile converge, drift audit green, local identities
    promoted without dropping established flows, regeneration bounded
    by the actually-diverged endpoint set."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    kv = EtcdBackend(host="127.0.0.1", port=injector.proxy("etcd").port,
                     lease_ttl=30.0, timeout=1.0)
    cfg = DaemonConfig(state_dir="", drift_audit_interval_s=0,
                       ct_checkpoint_interval_s=0,
                       enable_kvstore_survival=True,
                       kvstore_probe_interval_s=0.1,
                       kvstore_failure_threshold=2)
    d = Daemon(config=cfg, kvstore_backend=kv, node_name="n1")
    observer = EtcdBackend(port=etcd_server.port, lease_ttl=30.0)
    try:
        d.endpoint_create(1, ipv4=WEB_IP, labels=["k8s:id=web"])
        d.endpoint_create(2, ipv4=DB_IP, labels=["k8s:id=db"])
        # bystanders: endpoints the promotion must NOT regenerate
        for k in range(4):
            d.endpoint_create(10 + k, ipv4=f"10.200.1.{10 + k}",
                              labels=[f"k8s:id=bystander{k}"])
        rev = d.policy_add(rules_from_json(RULES_JSON))
        assert d.wait_for_policy_revision(rev, timeout=60)
        st = d.status()["kvstore"]
        assert st["mode"] == "ok" and st["backend"] == "EtcdBackend"

        disp = d.datapath.serving()
        slot = d.endpoints.lookup(2).table_slot
        # establish a long-lived flow web -> db:5432 (SYN then ACK)
        v, _ = _verdicts(disp, _recs(slot, 4, 5432, WEB_IP, 40000))
        assert (v == 0).all()
        v, _ = _verdicts(disp, _recs(slot, 4, 5432, WEB_IP, 40000,
                                     flags=0x10))
        assert (v == 0).all()

        # ---- blackhole etcd mid-run ----
        injector.blackhole("etcd")
        _wait_for(lambda: d.status()["kvstore"]["mode"] == "degraded",
                  msg="kvstore degraded")
        s1 = d.status()["kvstore"]["staleness-seconds"]
        time.sleep(0.4)
        st = d.status()["kvstore"]
        assert st["staleness-seconds"] > s1, "staleness must grow"
        assert "DEGRADED" in st["state"]
        assert st["breaker"] != "closed"

        # dataplane keeps serving bit-exact: drift audit replays the
        # live compiled tables against the host oracles
        rep = d.run_drift_audit()
        assert rep["status"] in ("ok", "idle")
        # established flow still forwards, denied still denied
        v, _ = _verdicts(disp, _recs(slot, 4, 5432, WEB_IP, 40000,
                                     flags=0x10))
        assert (v == 0).all()
        v, _ = _verdicts(disp, _recs(slot, 4, 9999, WEB_IP, 41000))
        assert (v < 0).all()

        # ---- endpoint created DURING the outage ----
        t0 = time.monotonic()
        ep3 = d.endpoint_create(3, ipv4=TMP_IP, labels=["k8s:id=tmp"])
        create_s = time.monotonic() - t0
        assert create_s < 5.0, \
            f"degraded create took {create_s:.1f}s (not failing fast)"
        local_id = ep3.security_identity
        assert is_local_scope_identity(local_id)
        assert d.wait_for_policy_revision(rev, timeout=60)
        st = d.status()["kvstore"]
        assert st["local-identities"] == 1
        assert st["journal-depth"] >= 1   # the ipcache upsert journaled

        # correct verdicts for the outage endpoint: tmp -> db:7000
        # allowed, anything else denied
        v, ident = _verdicts(disp, _recs(slot, 4, 7000, TMP_IP, 42000))
        assert (v == 0).all()
        assert (ident == local_id).all()
        v, _ = _verdicts(disp, _recs(slot, 4, 9999, TMP_IP, 43000))
        assert (v < 0).all()
        rep = d.run_drift_audit()
        assert rep["status"] in ("ok", "idle")

        # ---- reconnect ----
        regen_before = POLICY_REGENERATION_COUNT.total()
        injector.heal()
        _wait_for(lambda: d.status()["kvstore"]["mode"] == "ok",
                  msg="kvstore mode back to ok")
        _wait_for(lambda:
                  d.status()["kvstore"]["local-identities"] == 0,
                  msg="local identities promoted")
        ep3 = d.endpoints.lookup(3)
        new_id = ep3.security_identity
        assert not is_local_scope_identity(new_id)

        # converged: db's realized map now names the promoted identity
        def _db_promoted():
            state = PolicyMapState(d.endpoints.lookup(2).realized)
            keys = [k for k in state.keys() if k.dest_port == 7000]
            return keys and all(k.identity == new_id for k in keys)
        _wait_for(lambda: _db_promoted() and
                  d.wait_for_quiesce(0.1),
                  msg="referencing endpoint re-keyed")

        # regeneration bounded by the actually-diverged set (ep3 +
        # db), never the bystanders (a full-resync would be 7 builds)
        regens = POLICY_REGENERATION_COUNT.total() - regen_before
        assert regens <= 3, \
            f"{regens} regenerations — promotion fanned out too wide"

        # reconcile replayed the journal; the store now carries the
        # PROMOTED identity for the outage endpoint's IP
        st = d.status()["kvstore"]
        assert st["last-reconcile"] is not None
        assert st["last-reconcile"]["replayed"] >= 1

        def _published():
            raw = observer.get(f"cilium/state/ip/v1/default/{TMP_IP}/32")
            return raw is not None and \
                json.loads(raw.decode())["ID"] == new_id
        _wait_for(_published, msg="promoted identity published")

        # established flow survived the whole journey (CT untouched)
        v, _ = _verdicts(disp, _recs(slot, 4, 5432, WEB_IP, 40000,
                                     flags=0x10))
        assert (v == 0).all()
        # post-promotion verdicts stay correct and drift-free
        v, ident = _verdicts(disp, _recs(slot, 4, 7000, TMP_IP, 44000))
        assert (v == 0).all() and (ident == new_id).all()
        rep = d.run_drift_audit()
        assert rep["status"] in ("ok", "idle")
    finally:
        d.shutdown()
        kv.close()
        observer.close()


def test_daemon_flap_and_lease_expiry_repair(etcd_server, injector):
    """Flap etcd through the injector, then expire every server-side
    lease mid-outage: the reconcile's lease-grace repair re-asserts the
    reaped lease-backed keys (node registration, ipcache entries)."""
    kv = EtcdBackend(host="127.0.0.1", port=injector.proxy("etcd").port,
                     lease_ttl=30.0, timeout=1.0)
    cfg = DaemonConfig(state_dir="", drift_audit_interval_s=0,
                       ct_checkpoint_interval_s=0,
                       enable_kvstore_survival=True,
                       kvstore_probe_interval_s=0.1,
                       kvstore_failure_threshold=2,
                       enable_hubble=False)
    d = Daemon(config=cfg, kvstore_backend=kv, node_name="n1")
    observer = EtcdBackend(port=etcd_server.port, lease_ttl=30.0)
    try:
        d.register_node("10.0.0.1", "10.200.0.0/16")
        d.endpoint_create(1, ipv4=WEB_IP, labels=["k8s:id=web"])
        node_key = "cilium/state/nodes/v1/default/n1"
        ip_key = f"cilium/state/ip/v1/default/{WEB_IP}/32"
        _wait_for(lambda: observer.get(node_key) is not None,
                  msg="node registered")
        assert observer.get(ip_key) is not None

        # flap: partition/heal cycles — the guard must end closed
        injector.flap("etcd", cycles=2, period_s=0.3).join(timeout=10)
        _wait_for(lambda: d.status()["kvstore"]["mode"] == "ok",
                  msg="guard recovered from flap")

        # long outage: blackhole AND expire every lease server-side
        injector.blackhole("etcd")
        _wait_for(lambda: d.status()["kvstore"]["mode"] == "degraded",
                  msg="degraded after blackhole")
        assert injector.expire_leases() >= 1
        assert observer.get(node_key) is None, "lease reap expected"
        assert observer.get(ip_key) is None

        injector.heal()
        _wait_for(lambda: d.status()["kvstore"]["mode"] == "ok",
                  msg="reconciled after lease expiry")
        # the repair re-asserted our lease-backed keys (with a fresh
        # lease — the old one is gone server-side)
        _wait_for(lambda: observer.get(node_key) is not None,
                  msg="node registration repaired")
        _wait_for(lambda: observer.get(ip_key) is not None,
                  msg="ipcache entry repaired")
        rec = d.status()["kvstore"]["last-reconcile"]
        assert rec["repaired"] >= 1
        assert ("expire-leases" in
                [a for _p, a in injector.stats()["faults"]])
    finally:
        d.shutdown()
        kv.close()
        observer.close()


def test_injector_drives_apiserver_plane():
    """The injector's apiserver plane: partition opens the reflector's
    breaker (bounded probe cadence), heal closes it and syncs."""
    from cilium_tpu.k8s.client import K8sClient, Reflector
    from cilium_tpu.k8s.fake_apiserver import FakeAPIServer
    from cilium_tpu.utils.resilience import CircuitBreaker

    class _Sink:
        def __init__(self):
            self.events = []

        def enqueue_event(self, kind, action, obj):
            self.events.append((kind, action, obj))

    fake = FakeAPIServer().start()
    proxy = FaultProxy("127.0.0.1", fake.port).start()
    inj = ControlPlaneFaultInjector(apiserver=proxy)
    sink = _Sink()
    reflector = Reflector(
        K8sClient(f"http://127.0.0.1:{proxy.port}", timeout=2.0),
        "/api/v1/nodes", "node", sink,
        backoff_base=0.01, backoff_max=0.1,
        breaker=CircuitBreaker("cp-chaos-k8s", failure_threshold=3,
                               reset_timeout=0.1, max_reset=0.5))
    try:
        inj.partition("apiserver")
        reflector.start()
        _wait_for(lambda: reflector.breaker.state == "open",
                  timeout=10.0, msg="reflector breaker open")
        fake.upsert("nodes", {"metadata": {"name": "n1"}})
        inj.heal("apiserver")
        _wait_for(lambda: reflector.synced.is_set(), timeout=10.0,
                  msg="reflector synced after heal")
        _wait_for(lambda: reflector.breaker.state == "closed",
                  timeout=10.0, msg="breaker closed after heal")
    finally:
        reflector.stop()
        inj.close()
        proxy.close()
        fake.shutdown()


# ---------------------------------------- disabled path / status fix

def test_disabled_path_unwrapped_allocator_and_hard_failures():
    """enable_kvstore_survival=False (the default): no fallback
    allocator, no outage controller, and a dead backend surfaces hard
    errors exactly as before the change."""
    backend = _FlakyBackend()
    d = Daemon(config=DaemonConfig(state_dir="",
                                   drift_audit_interval_s=0,
                                   ct_checkpoint_interval_s=0,
                                   enable_hubble=False),
               kvstore_backend=backend, node_name="n1")
    try:
        assert isinstance(d.identity_allocator,
                          DistributedIdentityAllocator)
        assert not isinstance(d.identity_allocator,
                              FallbackIdentityAllocator)
        assert d.controllers.lookup("kvstore-outage") is None
        d.endpoint_create(1, ipv4=WEB_IP, labels=["k8s:id=web"])
        backend.fail = True
        # a NEW label set needs the kvstore: hard failure, no fallback
        with pytest.raises(Exception):
            d.endpoint_create(2, ipv4=DB_IP, labels=["k8s:id=db"])
        # ... but the status path now reports the staleness instead of
        # echoing 'ok' between calls (the satellite fix applies in
        # monitor-only mode too)
        st = d.status()["kvstore"]
        assert st["mode"] == "ok"          # degradation is opt-in
        assert st["staleness-seconds"] > 0
        assert st["consecutive-failures"] >= 1
        backend.fail = False
        d.endpoint_create(2, ipv4=DB_IP, labels=["k8s:id=db"])
        assert d.status()["kvstore"]["staleness-seconds"] == 0
    finally:
        backend.fail = False
        d.shutdown()


def test_controller_health_top_level_signal():
    """A controller failing >=3x consecutively surfaces as a top-level
    degraded signal in status(), and controller_runs_total counts
    per-run outcomes."""
    from cilium_tpu.utils.metrics import CONTROLLER_RUNS
    d = Daemon(config=DaemonConfig(state_dir="",
                                   drift_audit_interval_s=0,
                                   ct_checkpoint_interval_s=0,
                                   enable_hubble=False))
    try:
        assert d.status()["controller-health"]["status"] == "ok"
        fails_before = CONTROLLER_RUNS.value(
            labels={"name": "cp-chaos-wedged", "status": "failure"})

        from cilium_tpu.utils.controller import ControllerParams

        def boom():
            raise RuntimeError("wedged reconcile")

        d.controllers.update_controller(
            "cp-chaos-wedged",
            ControllerParams(do_func=boom, run_interval=0.01,
                             error_retry_base=0.01))
        _wait_for(lambda: d.status()["controller-health"]["failing"],
                  timeout=10.0, msg="controller-health degraded")
        ch = d.status()["controller-health"]
        assert ch["status"].startswith("DEGRADED")
        names = [f["name"] for f in ch["failing"]]
        assert "cp-chaos-wedged" in names
        wedged = next(f for f in ch["failing"]
                      if f["name"] == "cp-chaos-wedged")
        assert wedged["consecutive-failures"] >= 3
        assert "wedged reconcile" in wedged["last-error"]
        assert CONTROLLER_RUNS.value(
            labels={"name": "cp-chaos-wedged",
                    "status": "failure"}) > fails_before
        # healing the controller clears the signal
        d.controllers.update_controller(
            "cp-chaos-wedged",
            ControllerParams(do_func=lambda: None, run_interval=0.01))
        _wait_for(lambda: not
                  d.status()["controller-health"]["failing"],
                  timeout=10.0, msg="controller-health ok again")
    finally:
        d.shutdown()
