"""Sharding-spec lint: every device-table leaf has a declared
PartitionSpec in the canonical registry (parallel/specs.py).

The failure mode this guards: someone adds a leaf to ``FullTables``
(or the CT/flow state) and it silently defaults to replicated —
correct on one device, a capacity/memory lie on the mesh, and invisible
until a shard OOMs.  A new leaf without a registry entry is a test
failure, not a review nit.  The registry is also checked against
reality the other way: specs naming leaves that no longer exist are
stale docs and fail too.
"""

from jax.sharding import PartitionSpec

from cilium_tpu.parallel import specs
from cilium_tpu.parallel.mesh import DP_AXIS, EP_AXIS


def test_every_table_leaf_has_a_declared_spec():
    missing = specs.missing_specs()
    assert not missing, (
        "device-table leaves without a declared PartitionSpec in "
        "parallel/specs.py (new leaves must not silently default to "
        f"replicated): {missing}")


def test_no_stale_spec_entries():
    from cilium_tpu.datapath.lb import LB6Tables, LBTables
    from cilium_tpu.datapath.pipeline import DatapathTables, LPM6Tables
    nested = {
        "FullTables": {"datapath": DatapathTables, "lb": LBTables},
        "FullTables6": {"ipcache6": LPM6Tables, "pf6": LPM6Tables,
                        "lb6": LB6Tables},
    }
    stale = {}
    for cls, table in specs._table_classes().items():
        paths = set(specs.leaf_paths(cls,
                                     nested.get(cls.__name__, {})))
        extra = sorted(set(table) - paths)
        if extra:
            stale[cls.__name__] = extra
    assert not stale, f"specs name leaves that no longer exist: {stale}"


def test_registry_covers_the_core_tables():
    reg = specs.registry()
    for name in ("FullTables", "FullTables6", "DatapathTables",
                 "CTState", "FlowState", "Counters"):
        assert name in reg, f"{name} missing from the spec registry"


def test_specs_are_partition_specs_over_known_axes():
    for name, table in specs.registry().items():
        for leaf, spec in table.items():
            assert isinstance(spec, PartitionSpec), (name, leaf)
            for axis in spec:
                if axis is None:
                    continue
                axes = axis if isinstance(axis, tuple) else (axis,)
                for a in axes:
                    assert a in (DP_AXIS, EP_AXIS), \
                        f"{name}.{leaf} uses unknown mesh axis {a!r}"


def test_policy_tables_shard_endpoint_axis():
    """The tentpole invariant: the stacked policy tables' endpoint
    axis shards across ep (per-unit state residency), and the mutable
    CT/flow state is shard-local, never dp-sharded."""
    full = specs.FULL_TABLES_SPECS
    for leaf in ("datapath.key_id", "datapath.key_meta",
                 "datapath.value"):
        assert full[leaf] == specs.EP_ROWS, leaf
    assert full["ep_identity"] == specs.EP_VEC
    for leaf, spec in specs.CT_STATE_SPECS.items():
        assert spec == specs.SHARD_LOCAL, leaf
    for leaf, spec in specs.FLOW_STATE_SPECS.items():
        assert spec == specs.SHARD_LOCAL, leaf
