"""Sharding-spec lint: every device-table leaf has a declared
PartitionSpec in the canonical registry (parallel/specs.py).

The failure mode this guards: someone adds a leaf to ``FullTables``
(or the CT/flow state) and it silently defaults to replicated —
correct on one device, a capacity/memory lie on the mesh, and invisible
until a shard OOMs.  A new leaf without a registry entry is a test
failure, not a review nit.  The registry is also checked against
reality the other way: specs naming leaves that no longer exist are
stale docs and fail too.
"""

from jax.sharding import PartitionSpec

from cilium_tpu.parallel import specs
from cilium_tpu.parallel.mesh import DP_AXIS, EP_AXIS


def test_every_table_leaf_has_a_declared_spec():
    missing = specs.missing_specs()
    assert not missing, (
        "device-table leaves without a declared PartitionSpec in "
        "parallel/specs.py (new leaves must not silently default to "
        f"replicated): {missing}")


def test_no_stale_spec_entries():
    from cilium_tpu.datapath.lb import LB6Tables, LBTables
    from cilium_tpu.datapath.pipeline import DatapathTables, LPM6Tables
    nested = {
        "FullTables": {"datapath": DatapathTables, "lb": LBTables},
        "FullTables6": {"ipcache6": LPM6Tables, "pf6": LPM6Tables,
                        "lb6": LB6Tables},
    }
    stale = {}
    for cls, table in specs._table_classes().items():
        paths = set(specs.leaf_paths(cls,
                                     nested.get(cls.__name__, {})))
        extra = sorted(set(table) - paths)
        if extra:
            stale[cls.__name__] = extra
    assert not stale, f"specs name leaves that no longer exist: {stale}"


def test_registry_covers_the_core_tables():
    reg = specs.registry()
    for name in ("FullTables", "FullTables6", "DatapathTables",
                 "CTState", "FlowState", "Counters"):
        assert name in reg, f"{name} missing from the spec registry"


def test_specs_are_partition_specs_over_known_axes():
    for name, table in specs.registry().items():
        for leaf, spec in table.items():
            assert isinstance(spec, PartitionSpec), (name, leaf)
            for axis in spec:
                if axis is None:
                    continue
                axes = axis if isinstance(axis, tuple) else (axis,)
                for a in axes:
                    assert a in (DP_AXIS, EP_AXIS), \
                        f"{name}.{leaf} uses unknown mesh axis {a!r}"


def test_policy_tables_shard_endpoint_axis():
    """The tentpole invariant: the stacked policy tables' endpoint
    axis shards across ep (per-unit state residency), and the mutable
    CT/flow state is shard-local, never dp-sharded."""
    full = specs.FULL_TABLES_SPECS
    for leaf in ("datapath.key_id", "datapath.key_meta",
                 "datapath.value"):
        assert full[leaf] == specs.EP_ROWS, leaf
    assert full["ep_identity"] == specs.EP_VEC
    for leaf, spec in specs.CT_STATE_SPECS.items():
        assert spec == specs.SHARD_LOCAL, leaf
    for leaf, spec in specs.FLOW_STATE_SPECS.items():
        assert spec == specs.SHARD_LOCAL, leaf


# ---------------------------------------------------------------------------
# Dispatch-floor lint: the jitted step's flattened argument leaf count
# is pinned so new leaves can't silently regrow the per-batch host
# marshalling cost, and every packed-buffer group carries a declared
# PartitionSpec like the raw leaves it concatenates.
# ---------------------------------------------------------------------------

# the serving hot step's leaf budget: 2 grouped table buffers + the
# 3-buffer CT pack (split along XLA's copy-insertion boundaries — see
# conntrack.CTPack) + the counter pack + the [10, B] packed batch +
# the timestamp.  Raising this ceiling is a deliberate, reviewed act —
# each extra leaf is per-batch host dispatch work on every backend and
# every shard.
PACKED_STEP_LEAF_CEILING = 8
# flow aggregation adds the 2-leaf FlowState pack (keys buffer with
# the lost/updates accounting row + the uint32 counters; deliberately
# non-donated — hubble/aggregation.py).  Was 4 unpacked leaves (12
# total) before the flows pack joined the packing manifest.
PACKED_STEP_WITH_FLOWS_CEILING = 10
# v6 keeps the per-field packet batch (10 leaves) over the same
# grouped tables/state
V6_STEP_LEAF_CEILING = 17
# the L7 fast-verdict stage adds exactly TWO leaves to the payload-
# carrying step: the fused l7-dfa table group and the [B, W] payload
# lane (the per-slot l7_prog classification rides inside ep-int32) —
# pinned so the fast path can't silently regrow the dispatch floor
PACKED_STEP_WITH_L7_CEILING = PACKED_STEP_LEAF_CEILING + 2
# inline threat scoring likewise adds exactly TWO leaves: the fused
# threat-model group (quantized weights + config as ONE buffer) and
# the [6, T+1] shard-local ThreatState token-bucket/window buffer
PACKED_STEP_WITH_THREAT_CEILING = PACKED_STEP_LEAF_CEILING + 2
# traffic analytics adds exactly ONE leaf: the [R, W] shard-local
# A/B-epoch sketch buffer (sketches + candidate key tables +
# cardinality registers + control cell packed into a single int32
# array precisely so the dispatch floor pays one leaf, not four)
PACKED_STEP_WITH_ANALYTICS_CEILING = PACKED_STEP_LEAF_CEILING + 1


def _loaded_engine(flows: bool = False, l7_fast: bool = False,
                   threat: bool = False, analytics: bool = False):
    from bench import build_config1
    from cilium_tpu.datapath.engine import Datapath
    states, prefixes = build_config1(n_rules=10, n_endpoints=4)
    dp = Datapath(ct_slots=1 << 8)
    dp.telemetry_enabled = False
    if flows:
        dp.enable_flow_aggregation(slots=1 << 7)
        dp.enable_provenance()
    if l7_fast:
        from cilium_tpu.l7.fast import (FastProgramSpec,
                                        build_fast_programs)
        dp.enable_l7_fast(build_fast_programs(
            [FastProgramSpec(port=15001, protocol="http",
                             patterns=("GET\x00/x\x00.*",))],
            window=32))
    if threat:
        from cilium_tpu.threat import default_model
        dp.enable_threat(default_model(), buckets=1 << 8)
    if analytics:
        dp.enable_analytics(width=1 << 8)
    dp.load_policy(states, revision=1, ipcache_prefixes=prefixes)
    return dp


def test_jitted_step_leaf_ceiling():
    dp = _loaded_engine()
    counts = dp.dispatch_leaf_counts()
    assert counts["packed-step"] <= PACKED_STEP_LEAF_CEILING, counts
    # the acceptance floor: >= 4x fewer leaves than the legacy pytree
    assert counts["legacy-step"] >= 4 * counts["packed-step"], counts
    # the v6 step shares the grouped tables/state (only the per-field
    # packet batch stays unpacked)
    assert counts["v6-step"] <= V6_STEP_LEAF_CEILING, counts


def test_jitted_step_leaf_ceiling_with_flows_and_provenance():
    dp = _loaded_engine(flows=True)
    counts = dp.dispatch_leaf_counts()
    assert counts["packed-step"] <= PACKED_STEP_WITH_FLOWS_CEILING, \
        counts
    # the 2-leaf FlowState pack rides along non-donated, so the flows
    # variant's floor is 3x, not 4x (legacy counts its packed form
    # too — the leaf win there is CT/counters/tables)
    assert counts["legacy-step"] >= 3 * counts["packed-step"], counts


def test_jitted_step_leaf_ceiling_with_l7_fast():
    """The payload-carrying step: the fused DFA group + the payload
    lane are the ONLY new leaves, and an L7-enabled engine's manifest
    carries the l7-dfa group (its own group, so the no-L7 program
    keeps the exact pre-fast buffer list)."""
    from cilium_tpu.parallel import packing
    dp = _loaded_engine(l7_fast=True)
    counts = dp.dispatch_leaf_counts()
    assert counts["packed-step"] <= PACKED_STEP_WITH_L7_CEILING, counts
    assert packing.L7_DFA_GROUP in dp._manifest4.group_names()
    assert packing.L7_DFA_GROUP in dp._manifest6.group_names()
    # and the no-L7 engine's manifest does NOT carry it
    plain = _loaded_engine()
    assert packing.L7_DFA_GROUP not in plain._manifest4.group_names()


def test_jitted_step_leaf_ceiling_with_threat():
    """The threat-scoring step: the fused threat-model group + the
    ThreatState buffer are the ONLY new leaves, the model packs into
    its own group (the no-threat program keeps the exact pre-threat
    buffer list), and the token-bucket state carries a declared
    shard-local spec."""
    from cilium_tpu.parallel import packing
    dp = _loaded_engine(threat=True)
    counts = dp.dispatch_leaf_counts()
    assert counts["packed-step"] <= PACKED_STEP_WITH_THREAT_CEILING, \
        counts
    assert packing.THREAT_MODEL_GROUP in dp._manifest4.group_names()
    assert packing.THREAT_MODEL_GROUP in dp._manifest6.group_names()
    plain = _loaded_engine()
    assert packing.THREAT_MODEL_GROUP not in \
        plain._manifest4.group_names()
    # the token-bucket leaf is registered shard-local, like CT
    assert specs.THREAT_STATE_SPECS["state"] == specs.SHARD_LOCAL
    assert "ThreatState" in specs.registry()
    assert specs.PACKED_GROUP_SPECS[packing.THREAT_STATE_GROUP] == \
        specs.SHARD_LOCAL


def test_jitted_step_leaf_ceiling_with_analytics():
    """The analytics step: the ONE [R, W] AnalyticsState buffer is
    the only new leaf (sketches, key tables, cardinality registers
    and the epoch control cell all pack into it), and it carries a
    declared shard-local spec like CT/flow/threat state."""
    from cilium_tpu.parallel import packing
    dp = _loaded_engine(analytics=True)
    counts = dp.dispatch_leaf_counts()
    assert counts["packed-step"] <= \
        PACKED_STEP_WITH_ANALYTICS_CEILING, counts
    plain = _loaded_engine()
    assert plain.dispatch_leaf_counts()["packed-step"] <= \
        PACKED_STEP_LEAF_CEILING
    # the sketch leaf is registered shard-local, like CT
    assert specs.ANALYTICS_STATE_SPECS["state"] == specs.SHARD_LOCAL
    assert "AnalyticsState" in specs.registry()
    assert specs.PACKED_GROUP_SPECS[packing.ANALYTICS_STATE_GROUP] \
        == specs.SHARD_LOCAL


def test_every_packed_group_has_a_declared_spec():
    from cilium_tpu.parallel import packing
    dp = _loaded_engine(l7_fast=True)
    thr = _loaded_engine(threat=True)
    groups = (set(dp._manifest4.group_names())
              | set(dp._manifest6.group_names())
              | set(thr._manifest4.group_names())
              | {packing.CT_STATE_GROUP, packing.COUNTERS_GROUP,
                 packing.FLOW_STATE_GROUP, packing.THREAT_STATE_GROUP,
                 packing.ANALYTICS_STATE_GROUP})
    undeclared = groups - set(specs.PACKED_GROUP_SPECS)
    assert not undeclared, (
        "packed dispatch-buffer groups without a declared "
        f"PartitionSpec in specs.PACKED_GROUP_SPECS: {undeclared}")
    for name, spec in specs.PACKED_GROUP_SPECS.items():
        assert isinstance(spec, PartitionSpec), name
