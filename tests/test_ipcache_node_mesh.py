"""ipcache control plane, node registry, and clustermesh tests.

Mirrors the reference's pkg/ipcache tests (source precedence,
listener), pkg/node store/manager behavior, and clustermesh
multi-cluster sync with cluster-scoped identities.
"""

import threading
import time

import numpy as np
import pytest

from cilium_tpu.clustermesh import ClusterMesh, scope_identity
from cilium_tpu.compiler.lpm import oracle_lpm
from cilium_tpu.identity import RESERVED_WORLD, LocalIdentityAllocator
from cilium_tpu.ipcache import (SOURCE_AGENT_LOCAL, SOURCE_GENERATED,
                                SOURCE_K8S, SOURCE_KVSTORE,
                                DatapathLPMListener, IPCache,
                                IPIdentityWatcher, KVStoreIPCacheSyncer,
                                allocate_cidr_identities,
                                release_cidr_identities)
from cilium_tpu.kvstore.memory import InMemoryBackend, MemStore
from cilium_tpu.node import Node, NodeAddress, NodeManager, NodeRegistry


def wait_until(fn, timeout=5.0, interval=0.01):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return fn()


# ----------------------------------------------------------------- ipcache

def test_ipcache_source_precedence():
    c = IPCache()
    assert c.upsert("10.0.0.1", 300, SOURCE_KVSTORE)
    # lower-precedence k8s may not overwrite kvstore
    assert not c.upsert("10.0.0.1", 400, SOURCE_K8S)
    assert c.lookup_by_ip("10.0.0.1") == 300
    # higher-precedence agent-local wins
    assert c.upsert("10.0.0.1", 500, SOURCE_AGENT_LOCAL)
    assert c.lookup_by_ip("10.0.0.1") == 500
    # k8s cannot delete the agent-local entry either
    assert not c.delete("10.0.0.1", SOURCE_K8S)
    assert c.delete("10.0.0.1", SOURCE_AGENT_LOCAL)
    assert c.lookup_by_ip("10.0.0.1") is None
    with pytest.raises(ValueError):
        c.upsert("10.0.0.1", 1, "bogus-source")


def test_ipcache_listeners_and_reverse_index():
    c = IPCache()
    events = []
    c.upsert("10.1.0.0/16", 201, SOURCE_KVSTORE)
    # replay on registration delivers the existing entry
    c.add_listener(lambda mod, pair, old: events.append((mod, pair.prefix,
                                                         pair.identity)))
    assert events == [("upsert", "10.1.0.0/16", 201)]
    c.upsert("10.2.0.0/16", 202, SOURCE_KVSTORE)
    c.upsert("10.2.0.0/16", 203, SOURCE_KVSTORE)  # modify
    c.delete("10.1.0.0/16", SOURCE_KVSTORE)
    assert ("upsert", "10.2.0.0/16", 203) in events
    assert ("delete", "10.1.0.0/16", 201) in events
    assert c.lookup_by_identity(203) == ["10.2.0.0/16"]
    assert c.lookup_by_identity(202) == []


def test_ipcache_longest_prefix_host_side():
    c = IPCache()
    c.upsert("10.0.0.0/8", 100, SOURCE_KVSTORE)
    c.upsert("10.1.0.0/16", 200, SOURCE_KVSTORE)
    c.upsert("10.1.2.3", 300, SOURCE_AGENT_LOCAL)
    assert c.lookup_longest_prefix("10.1.2.3") == 300
    assert c.lookup_longest_prefix("10.1.9.9") == 200
    assert c.lookup_longest_prefix("10.9.9.9") == 100
    assert c.lookup_longest_prefix("192.168.0.1") is None
    # matches the compiled-LPM oracle on the same table
    prefixes = c.to_lpm_prefixes()
    for ip in ("10.1.2.3", "10.1.9.9", "10.9.9.9"):
        assert oracle_lpm(prefixes, ip) == c.lookup_longest_prefix(ip)


def test_ipcache_kvstore_distribution_two_agents():
    """Agent A publishes; agent B's watcher ingests (ipcache/kvstore.go)."""
    store = MemStore()
    be_a = InMemoryBackend(store)
    be_b = InMemoryBackend(store)

    cache_a, cache_b = IPCache(), IPCache()
    syncer = KVStoreIPCacheSyncer(be_a)
    cache_a.add_listener(syncer.listener(), replay=False)

    watcher = IPIdentityWatcher(be_b, cache_b)
    watcher.start()
    assert watcher.wait_synced(5)

    cache_a.upsert("10.0.1.5", 777, SOURCE_AGENT_LOCAL,
                   host_ip="192.168.1.10")
    assert wait_until(lambda: cache_b.lookup_by_ip("10.0.1.5") == 777)
    # the kvstore-sourced copy carries the host IP for encap
    pair = [p for p in cache_b.dump() if p.identity == 777][0]
    assert pair.host_ip == "192.168.1.10"
    assert pair.source == SOURCE_KVSTORE

    cache_a.delete("10.0.1.5", SOURCE_AGENT_LOCAL)
    assert wait_until(lambda: cache_b.lookup_by_ip("10.0.1.5") is None)
    watcher.stop()


def test_datapath_lpm_listener_recompiles():
    c = IPCache()
    compiled_holder = []
    listener = DatapathLPMListener(c, compiled_holder.append,
                                   min_interval=0.0)
    c.upsert("10.0.0.0/8", 100, SOURCE_KVSTORE)
    c.upsert("10.1.0.0/16", 200, SOURCE_KVSTORE)
    assert listener.flush(5)
    compiled = compiled_holder[-1]
    assert compiled.entry_count() == 2
    assert oracle_lpm(c.to_lpm_prefixes(), "10.1.2.3") == 200
    listener.shutdown()


def test_cidr_identity_allocation_roundtrip():
    alloc = LocalIdentityAllocator()
    cache = IPCache()
    idents = allocate_cidr_identities(alloc, cache,
                                      ["10.0.0.0/8", "192.168.1.0/24"])
    assert len(idents) == 2
    id1 = cache.lookup_by_ip("10.0.0.0/8")
    assert id1 == idents["10.0.0.0/8"].id >= 256
    # same prefix twice -> same identity (refcounted)
    again = allocate_cidr_identities(alloc, cache, ["10.0.0.0/8"])
    assert again["10.0.0.0/8"].id == id1
    # one release keeps it; the second frees and clears the cache
    assert release_cidr_identities(alloc, cache, again) == 0
    assert cache.lookup_by_ip("10.0.0.0/8") == id1
    assert release_cidr_identities(
        alloc, cache, {"10.0.0.0/8": idents["10.0.0.0/8"]}) == 1
    assert cache.lookup_by_ip("10.0.0.0/8") is None


# -------------------------------------------------------------------- nodes

def _node(name, ip, pod_cidr, cluster="default", cluster_id=0):
    return Node(name=name, cluster=cluster, cluster_id=cluster_id,
                addresses=[NodeAddress(type="InternalIP", ip=ip)],
                ipv4_alloc_cidr=pod_cidr)


def test_node_registry_two_agents_discover_each_other():
    store = MemStore()
    reg_a = NodeRegistry(InMemoryBackend(store))
    reg_b = NodeRegistry(InMemoryBackend(store))
    assert reg_a.wait_synced(5) and reg_b.wait_synced(5)

    reg_a.register_local(_node("node-a", "192.168.0.1", "10.1.0.0/16"))
    reg_b.register_local(_node("node-b", "192.168.0.2", "10.2.0.0/16"))
    assert wait_until(lambda: len(reg_a) == 2 and len(reg_b) == 2)
    names = [n.name for n in reg_a.nodes()]
    assert names == ["node-a", "node-b"]
    got = reg_a.get("default/node-b")
    assert got.get_node_ip() == "192.168.0.2"

    reg_b.unregister_local(_node("node-b", "192.168.0.2", "10.2.0.0/16"))
    assert wait_until(lambda: len(reg_a) == 1)
    reg_a.close()
    reg_b.close()


def test_node_manager_programs_tunnel_and_ipcache():
    cache = IPCache()
    mgr = NodeManager("default/node-a", ipcache=cache)
    peer = _node("node-b", "192.168.0.2", "10.2.0.0/16")
    mgr.node_updated(peer)
    assert mgr.tunnel_endpoint_for("10.2.0.0/16") == "192.168.0.2"
    assert cache.lookup_by_ip("10.2.0.0/16") == RESERVED_WORLD
    # pod-CIDR move reprograms
    moved = _node("node-b", "192.168.0.2", "10.3.0.0/16")
    mgr.node_updated(moved)
    assert mgr.tunnel_endpoint_for("10.2.0.0/16") is None
    assert mgr.tunnel_endpoint_for("10.3.0.0/16") == "192.168.0.2"
    # the local node programs nothing
    mgr.node_updated(_node("node-a", "192.168.0.1", "10.1.0.0/16"))
    assert mgr.tunnel_endpoint_for("10.1.0.0/16") is None
    mgr.node_deleted("default/node-b")
    assert mgr.tunnel_endpoint_for("10.3.0.0/16") is None
    assert cache.lookup_by_ip("10.3.0.0/16") is None


# -------------------------------------------------------------- clustermesh

def test_scope_identity_bits():
    assert scope_identity(3, 1000) == (3 << 16) | 1000
    assert scope_identity(0, 1000) == 1000
    # reserved identities stay unscoped
    assert scope_identity(3, RESERVED_WORLD) == RESERVED_WORLD


def test_clustermesh_syncs_remote_nodes_and_ips():
    remote_store = MemStore()
    # the "remote cluster" publishes a node + an ip mapping
    remote_reg = NodeRegistry(InMemoryBackend(remote_store))
    remote_reg.register_local(
        _node("r-node-1", "172.16.0.1", "10.9.0.0/16", cluster="east"))
    remote_cache = IPCache()
    syncer = KVStoreIPCacheSyncer(InMemoryBackend(remote_store))
    remote_cache.add_listener(syncer.listener(), replay=False)
    remote_cache.upsert("10.9.1.4", 2000, SOURCE_AGENT_LOCAL)

    local_cache = IPCache()
    seen_nodes = []
    mesh = ClusterMesh(ipcache=local_cache,
                       on_node_update=lambda n: seen_nodes.append(n))
    rc = mesh.add_cluster("east", 3,
                          lambda: InMemoryBackend(remote_store))
    assert rc.connected.wait(5)
    assert wait_until(lambda: len(seen_nodes) >= 1)
    assert seen_nodes[0].name == "r-node-1"
    assert seen_nodes[0].cluster_id == 3
    # remote identity arrives scoped with cluster bits
    assert wait_until(
        lambda: local_cache.lookup_by_ip("10.9.1.4") ==
        scope_identity(3, 2000))
    assert mesh.num_ready() == 1
    st = mesh.status()[0]
    assert st["name"] == "east" and st["ready"]

    mesh.remove_cluster("east")
    assert mesh.num_ready() == 0
    remote_reg.close()


def test_clustermesh_reconnects_after_failure():
    attempts = []
    store = MemStore()

    def flaky_factory():
        attempts.append(1)
        if len(attempts) < 3:
            raise ConnectionError("remote etcd down")
        return InMemoryBackend(store)

    mesh = ClusterMesh()
    rc = mesh.add_cluster("west", 2, flaky_factory)
    assert rc.connected.wait(10)
    assert len(attempts) == 3
    assert rc.failures == 2
    mesh.close()
