"""Endpoint lifecycle tests: state machine, identity, regeneration,
device-table sync, build queue.

Mirrors the reference's pkg/endpoint tests plus the syncPolicyMap /
buildqueue semantics (pkg/endpoint/bpf.go:607, pkg/buildqueue).
"""

import time

import numpy as np
import pytest

import jax.numpy as jnp

from cilium_tpu.compiler.policy_tables import oracle_verdict, pack_key
from cilium_tpu.endpoint import (DeviceTableManager, Endpoint,
                                 EndpointManager, EndpointState,
                                 StateTransitionError)
from cilium_tpu.identity import LocalIdentityAllocator
from cilium_tpu.labels import LabelArray, Labels
from cilium_tpu.ops.hashtab_ops import batched_lookup
from cilium_tpu.policy.api import (EndpointSelector, IngressRule, L7Rules,
                                   PortProtocol, PortRule, PortRuleHTTP,
                                   Rule)
from cilium_tpu.policy.mapstate import (EGRESS, INGRESS, PolicyKey,
                                        PolicyMapState, PolicyMapStateEntry)
from cilium_tpu.policy.repository import Repository
from cilium_tpu.proxy import ProxyManager


def es(*labels):
    return EndpointSelector.parse(*labels)


def mk_labels(*strs):
    return Labels.from_model(list(strs))


# ------------------------------------------------------------ state machine

def test_state_machine_valid_path():
    ep = Endpoint(1)
    assert ep.state == EndpointState.CREATING
    assert ep.set_state(EndpointState.WAITING_FOR_IDENTITY, "t")
    assert ep.set_state(EndpointState.READY, "t")
    assert ep.set_state(EndpointState.REGENERATING, "t")
    assert ep.set_state(EndpointState.READY, "t")
    assert ep.set_state(EndpointState.DISCONNECTING, "t")
    assert ep.set_state(EndpointState.DISCONNECTED, "t")


def test_state_machine_rejects_bad_moves():
    ep = Endpoint(1)
    # creating cannot jump straight to regenerating
    assert not ep.set_state(EndpointState.REGENERATING, "t")
    assert ep.state == EndpointState.CREATING
    ep.set_state(EndpointState.DISCONNECTING, "t")
    ep.set_state(EndpointState.DISCONNECTED, "t")
    # disconnected is terminal
    assert not ep.set_state(EndpointState.READY, "t")
    with pytest.raises(StateTransitionError):
        ep.set_state("bogus", "t")


def test_update_labels_allocates_identity():
    alloc = LocalIdentityAllocator()
    ep = Endpoint(5)
    changed = ep.update_labels(alloc, mk_labels("k8s:app=foo"))
    assert changed
    assert ep.state == EndpointState.READY
    first = ep.security_identity
    assert first >= 256
    # same labels -> same identity, no change
    assert not ep.update_labels(alloc, mk_labels("k8s:app=foo"))
    assert ep.security_identity == first
    # new labels -> new identity, old released
    assert ep.update_labels(alloc, mk_labels("k8s:app=bar"))
    assert ep.security_identity != first
    assert len(alloc) == 1  # foo refcount dropped to zero and was freed


# ------------------------------------------------------------- regeneration

def _policy_repo():
    repo = Repository()
    repo.add(Rule(endpoint_selector=es("id=server"), ingress=[
        IngressRule(from_endpoints=[es("id=client")]),
        IngressRule(to_ports=[PortRule(
            ports=[PortProtocol(port="80", protocol="TCP")])]),
    ]))
    return repo


def test_regenerate_policy_produces_delta_then_applies():
    repo = _policy_repo()
    alloc = LocalIdentityAllocator()
    client, _ = alloc.allocate(mk_labels("k8s:id=client"))
    other, _ = alloc.allocate(mk_labels("k8s:id=other"))

    ep = Endpoint(7, labels=mk_labels("k8s:id=server"))
    ep.update_labels(alloc, ep.labels)
    from cilium_tpu.identity import IdentityCache
    cache = IdentityCache.snapshot(alloc)

    res = ep.regenerate_policy(repo, cache)
    assert res.revision == repo.revision
    keys = {k for k, _ in res.adds}
    # L4 wildcard key for port 80 + L3 allow for client identity
    assert PolicyKey(identity=0, dest_port=80, nexthdr=6,
                     direction=INGRESS) in keys
    assert PolicyKey(identity=client.id, direction=INGRESS) in keys
    assert not any(k.identity == other.id and k.direction == INGRESS
                   and k.dest_port == 0 for k in keys)
    assert res.deletes == []
    ep.apply_regeneration(res)
    assert ep.policy_revision == res.revision

    # second regeneration with unchanged policy: empty delta
    res2 = ep.regenerate_policy(repo, cache)
    assert res2.adds == [] and res2.deletes == []

    # rule removal produces deletes (empty label set matches every rule)
    _, n_deleted = repo.delete_by_labels(LabelArray())
    assert n_deleted == 1
    res3 = ep.regenerate_policy(repo, cache)
    assert any(k.dest_port == 80 for k in res3.deletes)


def test_regeneration_with_l7_redirect_allocates_proxy_port():
    repo = Repository()
    repo.add(Rule(endpoint_selector=es("id=server"), ingress=[
        IngressRule(to_ports=[PortRule(
            ports=[PortProtocol(port="80", protocol="TCP")],
            rules=L7Rules(http=[PortRuleHTTP(method="GET")]))]),
    ]))
    alloc = LocalIdentityAllocator()
    proxy = ProxyManager()
    ep = Endpoint(9, labels=mk_labels("k8s:id=server"))
    ep.update_labels(alloc, ep.labels)
    from cilium_tpu.identity import IdentityCache
    cache = IdentityCache.snapshot(alloc)
    res = ep.regenerate_policy(repo, cache, proxy=proxy)
    assert len(res.redirects_added) == 1
    port = ep.proxy_redirects[res.redirects_added[0]]
    assert 10000 <= port < 20000
    # the wildcard L4 key carries the proxy port
    entry = dict(res.adds)[PolicyKey(identity=0, dest_port=80, nexthdr=6,
                                     direction=INGRESS)]
    assert entry.proxy_port == port
    # localhost allow rides on having a redirect (policy.go:263)
    assert any(k.identity == 1 for k, _ in res.adds)
    ep.apply_regeneration(res)

    # dropping the L7 rule removes the redirect
    repo.delete_by_labels(LabelArray())
    repo.add(Rule(endpoint_selector=es("id=server"), ingress=[
        IngressRule(to_ports=[PortRule(
            ports=[PortProtocol(port="80", protocol="TCP")])])]))
    res2 = ep.regenerate_policy(repo, cache, proxy=proxy)
    assert res2.redirects_removed and not ep.proxy_redirects
    assert len(proxy.redirects()) == 0


# ------------------------------------------------------- checkpoint/restore

def test_checkpoint_restore_roundtrip(tmp_path):
    alloc = LocalIdentityAllocator()
    ep = Endpoint(3, ipv4="10.0.0.3", container_name="web",
                  labels=mk_labels("k8s:app=web"))
    ep.update_labels(alloc, ep.labels)
    ep.realized[PolicyKey(identity=300, dest_port=443, nexthdr=6,
                          direction=INGRESS)] = \
        PolicyMapStateEntry(proxy_port=12345)
    ep.policy_revision = 17
    path = ep.write_checkpoint(str(tmp_path))

    import json
    with open(path) as f:
        snap = json.load(f)
    ep2 = Endpoint.restore(snap)
    assert ep2.id == 3 and ep2.ipv4 == "10.0.0.3"
    assert ep2.state == EndpointState.RESTORING
    assert ep2.policy_revision == 17
    assert ep2.realized[PolicyKey(identity=300, dest_port=443, nexthdr=6,
                                  direction=INGRESS)].proxy_port == 12345
    assert ep2.labels.to_array() == ep.labels.to_array()
    # restored endpoint can resume the lifecycle
    assert ep2.set_state(EndpointState.WAITING_TO_REGENERATE, "restore")


# ----------------------------------------------------- device table manager

def _lookup_all(mgr, ep_slot, state):
    """Device lookup of every key in ``state`` via the manager tensors."""
    keys = sorted(state.keys(), key=lambda k: (k.identity, k.dest_port,
                                               k.nexthdr, k.direction))
    packed = [pack_key(k) for k in keys]
    ka = jnp.asarray(np.array([p[0] for p in packed], np.uint32)
                     .view(np.int32))
    kb = jnp.asarray(np.array([p[1] for p in packed], np.uint32)
                     .view(np.int32))
    key_id, key_meta, value = mgr.tensors()
    found, val, _ = batched_lookup(key_id[ep_slot], key_meta[ep_slot],
                                   value[ep_slot], ka, kb, mgr.max_probe)
    return keys, np.asarray(found), np.asarray(val)


def test_table_manager_row_sync_and_lookup():
    mgr = DeviceTableManager(initial_endpoints=2, initial_slots=64)
    slot = mgr.attach(42)
    state = PolicyMapState()
    state[PolicyKey(identity=300, dest_port=80, nexthdr=6,
                    direction=INGRESS)] = PolicyMapStateEntry(proxy_port=0)
    state[PolicyKey(identity=0, dest_port=443, nexthdr=6,
                    direction=INGRESS)] = \
        PolicyMapStateEntry(proxy_port=11000)
    stats = mgr.sync_endpoint(42, state, revision=2)
    assert not stats["full_swap"]
    keys, found, val = _lookup_all(mgr, slot, state)
    assert found.all()
    for k, v in zip(keys, val):
        assert state[k].proxy_port == int(v)
    # second endpoint's row is independent
    slot2 = mgr.attach(43)
    assert slot2 != slot
    st2 = PolicyMapState()
    st2[PolicyKey(identity=999, dest_port=53, nexthdr=17,
                  direction=EGRESS)] = PolicyMapStateEntry()
    mgr.sync_endpoint(43, st2, revision=2)
    _, found2, _ = _lookup_all(mgr, slot, state)
    assert found2.all()  # untouched by the other row's sync


def test_table_manager_grow_on_capacity_and_slots():
    mgr = DeviceTableManager(initial_endpoints=1, initial_slots=8)
    mgr.attach(1)
    gen0 = mgr.generation
    mgr.attach(2)  # capacity grow => generation bump
    assert mgr.capacity >= 2 and mgr.generation == gen0 + 1

    # overflow the 8-slot row => slots grow, old rows still correct
    small = PolicyMapState()
    small[PolicyKey(identity=5000, dest_port=1, nexthdr=6,
                    direction=INGRESS)] = PolicyMapStateEntry()
    mgr.sync_endpoint(1, small, revision=1)
    big = PolicyMapState()
    for i in range(64):
        big[PolicyKey(identity=300 + i, dest_port=80, nexthdr=6,
                      direction=INGRESS)] = PolicyMapStateEntry()
    stats = mgr.sync_endpoint(2, big, revision=1)
    assert stats["full_swap"] and mgr.slots > 8
    keys, found, _ = _lookup_all(mgr, mgr.slot_of(2), big)
    assert found.all()
    _, found1, _ = _lookup_all(mgr, mgr.slot_of(1), small)
    assert found1.all()


def test_table_manager_detach_zeroes_row():
    mgr = DeviceTableManager(initial_endpoints=2, initial_slots=64)
    slot = mgr.attach(1)
    st = PolicyMapState()
    st[PolicyKey(identity=300, dest_port=80, nexthdr=6,
                 direction=INGRESS)] = PolicyMapStateEntry()
    mgr.sync_endpoint(1, st, revision=1)
    mgr.detach(1)
    key_id, key_meta, _ = mgr.tensors()
    assert int(np.asarray(key_meta[slot]).sum()) == 0
    # freed slot is reusable without growing the stack
    gen = mgr.generation
    mgr.attach(99)
    mgr.attach(100)
    assert mgr.capacity == 2 and mgr.generation == gen


# -------------------------------------------------------------- build queue

def test_endpoint_manager_parallel_builds_and_coalescing():
    built = []
    import threading
    gate = threading.Event()

    def regen(ep):
        gate.wait(2)
        built.append(ep.id)

    mgr = EndpointManager(regenerate_fn=regen, builders=4)
    alloc = LocalIdentityAllocator()
    for i in range(1, 5):
        ep = Endpoint(i, labels=mk_labels(f"k8s:app=a{i}"))
        ep.update_labels(alloc, ep.labels)
        mgr.insert(ep)
    assert len(mgr) == 4
    n = mgr.regenerate_all("test")
    assert n == 4
    # queueing again while builds are pending/running folds
    assert mgr.regenerate_all("test") == 0 or True
    gate.set()
    assert mgr.wait_for_quiesce(10)
    # every endpoint built at least once, and ends READY
    assert set(built) >= {1, 2, 3, 4}
    for ep in mgr.endpoints():
        assert ep.state == EndpointState.READY
    mgr.shutdown()


def test_endpoint_manager_rebuild_follow_up():
    import threading
    first_started = threading.Event()
    release_first = threading.Event()
    runs = []

    def regen(ep):
        runs.append(time.time())
        first_started.set()
        release_first.wait(2)

    mgr = EndpointManager(regenerate_fn=regen, builders=4)
    ep = Endpoint(1, labels=mk_labels("k8s:a=b"))
    ep.update_labels(LocalIdentityAllocator(), ep.labels)
    mgr.insert(ep)
    assert mgr.queue_regeneration(1)
    assert first_started.wait(5)
    # requested during an active build -> exactly one follow-up
    assert not mgr.queue_regeneration(1)
    assert not mgr.queue_regeneration(1)
    release_first.set()
    assert mgr.wait_for_quiesce(10)
    assert len(runs) == 2
    mgr.shutdown()


def test_endpoint_regen_failure_marks_not_ready():
    def regen(ep):
        raise RuntimeError("compile failed")

    mgr = EndpointManager(regenerate_fn=regen)
    ep = Endpoint(1, labels=mk_labels("k8s:a=b"))
    ep.update_labels(LocalIdentityAllocator(), ep.labels)
    mgr.insert(ep)
    mgr.queue_regeneration(1)
    assert mgr.wait_for_quiesce(10)
    assert ep.state == EndpointState.NOT_READY
    mgr.shutdown()


# ------------------------------------- end-to-end: repo -> tables -> oracle

def test_end_to_end_regen_to_device_verdicts():
    repo = _policy_repo()
    alloc = LocalIdentityAllocator()
    client, _ = alloc.allocate(mk_labels("k8s:id=client"))
    stranger, _ = alloc.allocate(mk_labels("k8s:id=stranger"))
    from cilium_tpu.identity import IdentityCache
    cache = IdentityCache.snapshot(alloc)

    tbl = DeviceTableManager()
    ep = Endpoint(11, labels=mk_labels("k8s:id=server"))
    ep.update_labels(alloc, ep.labels)
    tbl.attach(ep.id)
    res = ep.regenerate_policy(repo, cache)
    tbl.sync_endpoint(ep.id, ep.desired, res.revision)
    ep.apply_regeneration(res)

    slot = tbl.slot_of(ep.id)
    key_id, key_meta, value = tbl.tensors()
    # queries: (identity, dport, proto, dir) matrix vs the oracle
    queries = [(client.id, 80, 6, INGRESS), (client.id, 22, 6, INGRESS),
               (stranger.id, 80, 6, INGRESS), (stranger.id, 22, 6, INGRESS),
               (client.id, 0, 0, INGRESS)]
    from cilium_tpu.ops.hashtab_ops import batched_lookup as lk

    for ident, dport, proto, dirn in queries:
        want = oracle_verdict(ep.realized, ident, dport, proto, dirn)
        # reproduce the 3-stage device lookup on the manager's row
        stages = [(ident, dport, proto), (ident, 0, 0), (0, dport, proto)]
        got = -1
        for sid, sport, sproto in stages:
            pk = pack_key(PolicyKey(identity=sid, dest_port=sport,
                                    nexthdr=sproto, direction=dirn))
            ka = jnp.asarray(np.array([pk[0]], np.uint32).view(np.int32))
            kb = jnp.asarray(np.array([pk[1]], np.uint32).view(np.int32))
            f, v, _ = lk(key_id[slot], key_meta[slot], value[slot], ka, kb,
                         tbl.max_probe)
            if bool(np.asarray(f)[0]):
                got = int(np.asarray(v)[0]) if sid != ident or \
                    (sport, sproto) != (0, 0) else 0
                break
        assert got == want, (ident, dport, want, got)


# --------------------------------------------- review-regression coverage

def test_builders_survive_repeated_failures():
    fails = []

    def regen(ep):
        fails.append(ep.id)
        raise RuntimeError("boom")

    mgr = EndpointManager(regenerate_fn=regen, builders=4)
    alloc = LocalIdentityAllocator()
    for i in range(1, 7):
        ep = Endpoint(i, labels=mk_labels(f"k8s:app=f{i}"))
        ep.update_labels(alloc, ep.labels)
        mgr.insert(ep)
    mgr.regenerate_all("fail-round")
    assert mgr.wait_for_quiesce(10)
    assert len(fails) == 6
    # workers are still alive: a new (succeeding) round drains fine
    ok = []
    mgr.regenerate_fn = lambda ep: ok.append(ep.id)
    for ep in mgr.endpoints():
        ep.set_state(EndpointState.WAITING_TO_REGENERATE, "retry")
        mgr.queue_regeneration(ep.id)
    assert mgr.wait_for_quiesce(10)
    assert len(ok) == 6
    mgr.shutdown()


def test_restore_stale_option_keeps_rest():
    snap = {"id": 1, "labels": [], "options": {
        "Policy": 0, "SomeRetiredOption": 1, "Conntrack": 1}}
    ep = Endpoint.restore(snap)
    assert not ep.opts.is_enabled("Policy")
    assert ep.opts.is_enabled("Conntrack")


def test_restoring_endpoint_builds_directly():
    built = []
    mgr = EndpointManager(regenerate_fn=lambda ep: built.append(ep.id))
    ep = Endpoint.restore({"id": 4, "labels": ["k8s:a=b"]})
    assert ep.state == EndpointState.RESTORING
    mgr.insert(ep)
    mgr.queue_regeneration(4)
    assert mgr.wait_for_quiesce(10)
    assert built == [4]
    assert ep.state == EndpointState.READY
    mgr.shutdown()


def test_table_manager_non_pow2_slots():
    mgr = DeviceTableManager(initial_endpoints=2, initial_slots=100)
    assert mgr.slots == 128
    mgr.attach(1)
    st = PolicyMapState()
    for i in range(200):  # force slot growth through _grow's retry loop
        st[PolicyKey(identity=300 + i, dest_port=80, nexthdr=6,
                     direction=INGRESS)] = PolicyMapStateEntry()
    stats = mgr.sync_endpoint(1, st, revision=1)
    assert stats["full_swap"]
    assert mgr.slots >= 256 and (mgr.slots & (mgr.slots - 1)) == 0
    _, found, _ = _lookup_all(mgr, mgr.slot_of(1), st)
    assert found.all()
