"""FunctionQueue ordering/retry semantics + the k8s watcher's async,
resourceVersion-deduped dispatch (pkg/serializer + pkg/versioned
analogs, wired the way daemon/k8s_watcher.go wires serializers)."""

import threading
import time

import pytest

from cilium_tpu.daemon import Daemon
from cilium_tpu.daemon.daemon import DaemonConfig
from cilium_tpu.k8s.watcher import K8sWatcher
from cilium_tpu.utils.serializer import FunctionQueue


def test_function_queue_preserves_order():
    fq = FunctionQueue()
    out = []
    for i in range(200):
        fq.enqueue(lambda i=i: out.append(i))
    assert fq.wait_idle(10)
    assert out == list(range(200))
    fq.stop()


def test_function_queue_retries_then_gives_up():
    fq = FunctionQueue()
    calls = []

    def fails():
        calls.append(1)
        raise RuntimeError("boom")

    # retry twice, then drop; the queue keeps running afterwards
    fq.enqueue(fails, lambda n: n <= 2)
    done = []
    fq.enqueue(lambda: done.append(1))
    assert fq.wait_idle(10)
    assert len(calls) == 3 and done == [1]
    fq.stop()


def test_function_queue_concurrent_producers_serialize():
    fq = FunctionQueue()
    active = []
    overlap = []

    def work(i):
        active.append(i)
        if len(active) > 1:
            overlap.append(i)
        time.sleep(0.001)
        active.remove(i)

    threads = [threading.Thread(
        target=lambda s=s: [fq.enqueue(lambda i=i: work(i))
                            for i in range(s * 50, s * 50 + 50)])
        for s in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert fq.wait_idle(20)
    assert overlap == []  # never two handlers in flight
    fq.stop()


def test_function_queue_rejects_after_stop():
    fq = FunctionQueue()
    fq.stop()
    with pytest.raises(RuntimeError):
        fq.enqueue(lambda: None)


# ------------------------------------------------- watcher dispatch

def _svc(name, ip, port, rv):
    return {"metadata": {"name": name, "namespace": "default",
                         "resourceVersion": rv},
            "spec": {"clusterIP": ip,
                     "ports": [{"port": port, "protocol": "TCP"}]}}


def test_watcher_enqueue_applies_in_order_and_dedups():
    d = Daemon(config=DaemonConfig())
    try:
        w = K8sWatcher(d)
        key = ("default", "s1")
        assert w.enqueue_event("service", "add",
                               _svc("s1", "10.254.0.9", 80, "5"))
        # stale duplicate (same rv) and older rv are both dropped
        assert not w.enqueue_event("service", "modify",
                                   _svc("s1", "10.254.0.9", 81, "5"))
        assert not w.enqueue_event("service", "modify",
                                   _svc("s1", "10.254.0.9", 82, "3"))
        # newer rv applies
        assert w.enqueue_event("service", "modify",
                               _svc("s1", "10.254.0.9", 90, "6"))
        assert w.wait_idle(10)
        # the watcher applied exactly the two fresh events, in order
        assert w.events_by_kind.get("service") == 2
        assert w._services[key]["ports"][0]["port"] == 90
        # delete APPLIES (both action spellings normalize) and clears
        # the version record so a re-add with any rv applies
        assert w.enqueue_event(
            "service", "delete",
            _svc("s1", "10.254.0.9", 90, "7"))
        assert w.wait_idle(10)
        assert key not in w._services  # delete really removed it
        assert w.enqueue_event("service", "added",
                               _svc("s1", "10.254.0.9", 80, "1"))
        assert w.wait_idle(10)
        assert key in w._services
        w.stop()
    finally:
        d.shutdown()


def test_watcher_enqueue_never_blocks_on_slow_handler():
    d = Daemon(config=DaemonConfig())
    try:
        w = K8sWatcher(d)
        orig = w.on_namespace
        w.on_namespace = lambda a, o: (time.sleep(0.4), orig(a, o))
        t0 = time.time()
        w.enqueue_event("namespace", "add", {
            "metadata": {"name": "slowns", "resourceVersion": "1"},
            "labels": {}})
        w.enqueue_event("service", "add",
                        _svc("fast", "10.254.0.10", 80, "1"))
        # the informer-side thread returns immediately; application
        # happens behind the queues
        assert time.time() - t0 < 0.2, "enqueue blocked on handler"
        assert w.wait_idle(10)
        w.stop()
    finally:
        d.shutdown()


def test_watcher_failed_handler_unblocks_resync():
    """A handler that exhausts its retries must roll back the
    resourceVersion record so the apiserver's identical resync is not
    dropped as stale."""
    d = Daemon(config=DaemonConfig())
    try:
        w = K8sWatcher(d)
        boom = {"n": 2}
        orig = w.on_service

        def flaky(a, o):
            if boom["n"] > 0:
                boom["n"] -= 1
                raise RuntimeError("transient")
            orig(a, o)

        w.on_service = flaky
        # no retries: first delivery fails and is dropped...
        assert w.enqueue_event("service", "add",
                               _svc("s2", "10.254.0.11", 80, "9"))
        assert w.wait_idle(10)
        assert ("default", "s2") not in w._services
        # ...but the resync with the SAME rv must now apply
        assert w.enqueue_event("service", "add",
                               _svc("s2", "10.254.0.11", 80, "9"))
        assert w.wait_idle(10)
        assert not boom["n"]  # second failure consumed
        assert w.enqueue_event("service", "add",
                               _svc("s2", "10.254.0.11", 80, "9"))
        assert w.wait_idle(10)
        assert ("default", "s2") in w._services
        w.stop()
    finally:
        d.shutdown()


def test_watcher_rejects_events_after_stop():
    d = Daemon(config=DaemonConfig())
    try:
        w = K8sWatcher(d)
        w.enqueue_event("service", "add",
                        _svc("s3", "10.254.0.12", 80, "1"))
        assert w.wait_idle(10)
        w.stop()
        with pytest.raises(RuntimeError):
            w.enqueue_event("service", "add",
                            _svc("s4", "10.254.0.13", 80, "1"))
        assert not w._queues  # no leaked fresh queue
    finally:
        d.shutdown()


def test_watcher_opaque_resource_versions_bypass_dedup():
    """Non-decimal resourceVersions (k8s declares them opaque) must
    not crash the informer thread; they simply skip dedup."""
    d = Daemon(config=DaemonConfig())
    try:
        w = K8sWatcher(d)
        ev = _svc("sx", "10.254.0.20", 80, "v12-not-a-number")
        assert w.enqueue_event("service", "add", ev)
        assert w.enqueue_event("service", "modify", ev)  # no dedup
        assert w.wait_idle(10)
        assert w.events_by_kind.get("service") == 2
        w.stop()
    finally:
        d.shutdown()


def test_endpoint_create_rollback_frees_slot_and_identity():
    """Review regression: a failed create must not leak the device
    table slot or the identity refcount."""
    d = Daemon(config=DaemonConfig())
    try:
        idents_before = len(d.identity_allocator)
        slots_before = len(d.table_mgr._slot_of)
        orig = d.datapath.set_endpoint_identity
        d.datapath.set_endpoint_identity = \
            lambda *a, **k: (_ for _ in ()).throw(RuntimeError("boom"))
        with pytest.raises(RuntimeError):
            d.endpoint_create(888, ipv4="10.200.0.88",
                              labels=["k8s:app=ghost"])
        d.datapath.set_endpoint_identity = orig
        assert d.endpoints.lookup(888) is None
        assert "10.200.0.88" not in d.ipam.allocated()
        assert d.ipcache.lookup_by_ip("10.200.0.88") is None
        assert len(d.identity_allocator) == idents_before
        assert len(d.table_mgr._slot_of) == slots_before
        # the id and IP are fully reusable
        d.endpoint_create(888, ipv4="10.200.0.88",
                          labels=["k8s:app=ghost"])
        assert d.wait_for_quiesce(10)
    finally:
        d.shutdown()
