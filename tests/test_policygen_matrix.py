"""Generated policy matrices, end to end.

The analog of the reference's test/helpers/policygen (models.go:70-128):
combinatorially generate rule specs (L3 / L4 / L7 / L4-wildcard x
source selectors x ports), load them into a LIVE daemon, and compare —
for every (src endpoint, dst endpoint, port) flow — three
independently-computed answers:

  1. the repository oracle (allows_ingress with dports — the
     reference's own source of truth for verdicts),
  2. the device datapath verdict (full pipeline on the realized
     tables),
  3. the C++ host fast path (vc_classify_batch over the same state),

plus an expected-redirect bit derived straight from the generated
specs (a covering rule with HTTP L7 must yield verdict > 0).
"""

import numpy as np
import pytest

from cilium_tpu.daemon import Daemon
from cilium_tpu.daemon.daemon import DaemonConfig
from cilium_tpu.datapath.engine import make_full_batch
from cilium_tpu.labels import LabelArray
from cilium_tpu.policy.api import (Decision, EgressRule,
                                   EndpointSelector, IngressRule,
                                   L7Rules, PortProtocol, PortRule,
                                   PortRuleHTTP, Rule)
from cilium_tpu.policy.trace import Port, SearchContext

APPS = ["web", "db", "cache", "api"]
PORTS = [80, 443, 8080]
STRANGER_PORT = 7


def _gen_rules(rng):
    """Random rule specs; returns (rules, specs) where each spec is
    (dst_app, src_app_or_None, port_or_None, has_l7)."""
    rules, specs = [], []
    for _ in range(rng.integers(3, 8)):
        dst = APPS[rng.integers(0, len(APPS))]
        kind = rng.integers(0, 4)
        src = APPS[rng.integers(0, len(APPS))] if kind != 3 else None
        froms = [EndpointSelector.parse(f"app={src}")] if src else []
        if kind == 0:                        # L3-only
            rules.append(Rule(
                endpoint_selector=EndpointSelector.parse(f"app={dst}"),
                ingress=[IngressRule(from_endpoints=froms)]))
            specs.append((dst, src, None, False))
            continue
        port = PORTS[rng.integers(0, len(PORTS))]
        # L7 on targeted (kind 2) and sometimes on wildcard rules
        has_l7 = kind == 2 or (kind == 3 and rng.random() < 0.3)
        pr = PortRule(
            ports=[PortProtocol(port=str(port), protocol="TCP")],
            rules=L7Rules(http=[PortRuleHTTP(method="GET",
                                             path="/allowed/.*")])
            if has_l7 else None)
        rules.append(Rule(
            endpoint_selector=EndpointSelector.parse(f"app={dst}"),
            ingress=[IngressRule(from_endpoints=froms, to_ports=[pr])]))
        specs.append((dst, src, port, has_l7))
    # occasionally a FromRequires rule: deny-precedence must hold
    # through the whole stack (repository.go FromRequires matrices)
    if rng.random() < 0.4:
        dst = APPS[rng.integers(0, len(APPS))]
        req = APPS[rng.integers(0, len(APPS))]
        rules.append(Rule(
            endpoint_selector=EndpointSelector.parse(f"app={dst}"),
            ingress=[IngressRule(
                from_requires=[EndpointSelector.parse(f"app={req}")])]))
    return rules, specs


def _expect_redirect(specs, src_app, dst_app, port):
    """Independent redirect derivation from the generated specs: some
    covering rule carries HTTP L7 for this flow."""
    for dst, src, p, has_l7 in specs:
        if has_l7 and dst == dst_app and p == port and \
                (src is None or src == src_app):
            return True
    return False


def _gen_egress_rules(rng):
    """Random EGRESS rules: L3-only / L4 / dst-wildcard shapes."""
    rules = []
    for _ in range(rng.integers(2, 6)):
        src = APPS[rng.integers(0, len(APPS))]
        kind = rng.integers(0, 3)
        dst = APPS[rng.integers(0, len(APPS))] if kind != 2 else None
        tos = [EndpointSelector.parse(f"app={dst}")] if dst else []
        if kind == 0:                       # L3-only egress
            rules.append(Rule(
                endpoint_selector=EndpointSelector.parse(f"app={src}"),
                egress=[EgressRule(to_endpoints=tos)]))
            continue
        port = PORTS[rng.integers(0, len(PORTS))]
        pr = PortRule(ports=[PortProtocol(port=str(port),
                                          protocol="TCP")])
        rules.append(Rule(
            endpoint_selector=EndpointSelector.parse(f"app={src}"),
            egress=[EgressRule(to_endpoints=tos, to_ports=[pr])]))
    return rules


@pytest.mark.parametrize("seed", [3, 11])
def test_policygen_matrix_egress(seed):
    """Three-way agreement for the EGRESS direction: repository
    oracle (allows_egress), the device datapath with direction=1
    (the from-container path, bpf_lxc.c handle_ipv4_from_lxc), and
    the C++ host fast path."""
    rng = np.random.default_rng(seed)
    d = Daemon(config=DaemonConfig())
    try:
        eps = {}
        for i, app in enumerate(APPS):
            eps[app] = d.endpoint_create(
                200 + i, ipv4=f"10.200.8.{10 + i}",
                labels=[f"k8s:app={app}"])
        rules = _gen_egress_rules(rng)
        d.policy_add(rules)
        assert d.wait_for_quiesce(30)

        flows = [(src, dst, port)
                 for src in APPS for dst in APPS if src != dst
                 for port in PORTS + [STRANGER_PORT]]
        expected = []
        for src, dst, port in flows:
            ctx = SearchContext(
                from_labels=LabelArray.parse_select(f"app={src}"),
                to_labels=LabelArray.parse_select(f"app={dst}"),
                dports=[Port(port, "TCP")])
            expected.append(d.repo.allows_egress(ctx))

        batch = make_full_batch(
            endpoint=[eps[src].table_slot for src, _, _ in flows],
            saddr=[eps[src].ipv4 for src, _, _ in flows],
            daddr=[eps[dst].ipv4 for _, dst, _ in flows],
            sport=[46000 + i for i in range(len(flows))],
            dport=[p for _, _, p in flows],
            direction=[1] * len(flows))
        verdict, _ev, identity, _nat = d.datapath.process(batch)
        v = np.asarray(verdict)
        ids = np.asarray(identity)
        for i, (src, dst, port) in enumerate(flows):
            assert ids[i] == eps[dst].security_identity, (dst, ids[i])
            if expected[i] == Decision.ALLOWED:
                assert v[i] >= 0, \
                    f"seed {seed} egress {src}->{dst}:{port} " \
                    f"oracle ALLOWED, device {v[i]}"
            else:
                assert v[i] < 0, \
                    f"seed {seed} egress {src}->{dst}:{port} " \
                    f"oracle {expected[i]}, device {v[i]}"

        # host fast path agrees on the egress direction too
        if d.host_path is not None:
            for src in APPS:
                rows = [i for i, f in enumerate(flows) if f[0] == src]
                hv = d.host_path.classify(
                    eps[src].id,
                    np.array([eps[flows[i][1]].security_identity
                              for i in rows], np.uint32),
                    np.array([flows[i][2] for i in rows], np.int32),
                    np.full(len(rows), 6, np.int32),
                    np.ones(len(rows), np.int32))
                for j, i in enumerate(rows):
                    same = (hv[j] < 0) == (v[i] < 0)
                    assert same, \
                        f"seed {seed} egress host/device diverge on " \
                        f"{flows[i]}: host {hv[j]} device {v[i]}"
    finally:
        d.shutdown()


@pytest.mark.parametrize("seed", [1, 7, 23])
def test_policygen_matrix_oracle_device_host_agree(seed):
    rng = np.random.default_rng(seed)
    d = Daemon(config=DaemonConfig())
    try:
        eps = {}
        for i, app in enumerate(APPS):
            eps[app] = d.endpoint_create(
                100 + i, ipv4=f"10.200.9.{10 + i}",
                labels=[f"k8s:app={app}"])
        rules, specs = _gen_rules(rng)
        d.policy_add(rules)
        assert d.wait_for_quiesce(30)

        flows = []     # (src_app, dst_app, port)
        for src in APPS:
            for dst in APPS:
                if src == dst:
                    continue
                for port in PORTS + [STRANGER_PORT]:
                    flows.append((src, dst, port))

        # oracle: the repository's own verdict for each flow
        expected = []
        for src, dst, port in flows:
            ctx = SearchContext(
                from_labels=LabelArray.parse_select(f"app={src}"),
                to_labels=LabelArray.parse_select(f"app={dst}"),
                dports=[Port(port, "TCP")])
            expected.append(d.repo.allows_ingress(ctx))

        # device: one batch, fresh source ports (CT_NEW everywhere)
        batch = make_full_batch(
            endpoint=[eps[dst].table_slot for _, dst, _ in flows],
            saddr=[eps[src].ipv4 for src, _, _ in flows],
            daddr=[eps[dst].ipv4 for _, dst, _ in flows],
            sport=[40000 + i for i in range(len(flows))],
            dport=[p for _, _, p in flows],
            direction=[0] * len(flows))
        verdict, _ev, identity, _nat = d.datapath.process(batch)
        v = np.asarray(verdict)
        ids = np.asarray(identity)

        for i, (src, dst, port) in enumerate(flows):
            want = expected[i]
            assert ids[i] == eps[src].security_identity, (src, ids[i])
            if want == Decision.ALLOWED:
                assert v[i] >= 0, \
                    f"seed {seed} flow {src}->{dst}:{port} " \
                    f"oracle ALLOWED, device {v[i]}"
                if _expect_redirect(specs, src, dst, port):
                    assert v[i] > 0, \
                        f"seed {seed} {src}->{dst}:{port} should redirect"
            else:
                assert v[i] < 0, \
                    f"seed {seed} flow {src}->{dst}:{port} " \
                    f"oracle {want}, device {v[i]}"

        # host fast path agrees with the device for every flow
        if d.host_path is not None:
            for dst in APPS:
                rows = [i for i, f in enumerate(flows) if f[1] == dst]
                hv = d.host_path.classify(
                    eps[dst].id,
                    np.array([eps[flows[i][0]].security_identity
                              for i in rows], np.uint32),
                    np.array([flows[i][2] for i in rows], np.int32),
                    np.full(len(rows), 6, np.int32),
                    np.zeros(len(rows), np.int32))
                for j, i in enumerate(rows):
                    same_sign = (hv[j] < 0) == (v[i] < 0) and \
                        (hv[j] > 0) == (v[i] > 0)
                    assert same_sign, \
                        f"seed {seed} host/device diverge on " \
                        f"{flows[i]}: host {hv[j]} device {v[i]}"
    finally:
        d.shutdown()


def test_policygen_matrix_v6():
    """Generated matrices for the IPv6 path: random mapstates + v6
    prefixes; every flow's device verdict (full_datapath_step6) and
    resolved identity must match the scalar oracle + a host LPM."""
    import ipaddress
    from cilium_tpu.compiler.policy_tables import oracle_verdict
    from cilium_tpu.datapath.engine import Datapath, make_full_batch6
    from cilium_tpu.identity import RESERVED_WORLD
    from cilium_tpu.policy.mapstate import (INGRESS, PolicyKey,
                                            PolicyMapState,
                                            PolicyMapStateEntry)
    rng = np.random.default_rng(17)
    idents = [700 + i for i in range(6)]
    prefixes = {}
    for i, ident in enumerate(idents):
        plen = int(rng.choice([48, 56, 64]))
        net = ipaddress.ip_network(
            f"2001:db8:{i + 1:x}::/{plen}", strict=False)
        prefixes[str(net)] = ident

    st = PolicyMapState()
    rules = []  # (identity, port) installed allows
    for _ in range(12):
        ident = int(rng.choice(idents))
        port = int(rng.integers(1, 1 << 16))
        st[PolicyKey(identity=ident, dest_port=port, nexthdr=6,
                     direction=INGRESS)] = PolicyMapStateEntry(
            proxy_port=int(rng.integers(0, 2)) * 14001)
        rules.append((ident, port))
    # one L3-only and one L4-wildcard entry exercise stages 2/3
    st[PolicyKey(identity=idents[0],
                 direction=INGRESS)] = PolicyMapStateEntry()
    st[PolicyKey(identity=0, dest_port=443, nexthdr=6,
                 direction=INGRESS)] = PolicyMapStateEntry()

    dp = Datapath(ct_slots=1 << 10, ct_probe=4)
    dp.load_policy([st], revision=1, ipcache_prefixes={})
    dp.load_ipcache6(prefixes)

    def host_identity(addr):
        # the shared scalar LPM oracle (compiler/lpm.py) — one
        # reference implementation, not a per-test re-derivation
        from cilium_tpu.compiler.lpm import LPM_MISS, oracle_lpm
        v = oracle_lpm(prefixes, addr)
        return RESERVED_WORLD if v == LPM_MISS else v

    flows = []
    for k in range(120):
        if k % 3 == 0:            # address inside a known prefix
            pick = list(prefixes)[rng.integers(0, len(prefixes))]
            net = ipaddress.ip_network(pick)
            addr = str(net.network_address + int(rng.integers(1, 999)))
        else:                      # mix of known + stranger space
            addr = f"2001:db8:{rng.integers(1, 16):x}::{k + 1:x}" \
                if k % 3 == 1 else f"fd00::{k + 1:x}"
        # 40/20/40: installed rule ports / the 443 L4-wildcard /
        # uniform strangers — every lookup stage gets real coverage
        roll = rng.random()
        if roll < 0.4:
            port = rules[rng.integers(0, len(rules))][1]
        elif roll < 0.6:
            port = 443  # hits the (identity=0, 443) wildcard entry
        else:
            port = int(rng.integers(1, 1 << 16))
        flows.append((addr, port))

    batch = make_full_batch6(
        endpoint=[0] * len(flows),
        saddr=[a for a, _ in flows],
        daddr=["2001:db8:ff::1"] * len(flows),
        sport=[47000 + i for i in range(len(flows))],
        dport=[p for _, p in flows],
        direction=[0] * len(flows))
    verdict, _ev, identity, _n = dp.process6(batch, now=50)
    v = np.asarray(verdict)
    ids = np.asarray(identity)
    for i, (addr, port) in enumerate(flows):
        want_id = host_identity(addr)
        assert ids[i] == want_id, (addr, ids[i], want_id)
        want_v = oracle_verdict(st, want_id, port, 6, INGRESS)
        assert v[i] == want_v, \
            f"{addr}:{port} id={want_id} device {v[i]} oracle {want_v}"


def test_policygen_matrix_v6_icmp6():
    """ICMPv6 rows woven into a generated v6 matrix: NS/echo for the
    router answer locally regardless of policy, NS for other targets
    drop, and every other ICMPv6 flow gets the oracle's verdict for
    (identity, 0, 58) — the reference polices ICMPv6 at the L3/proto
    level (ipv6_policy reads ports only for TCP/UDP)."""
    import ipaddress
    from cilium_tpu.compiler.policy_tables import oracle_verdict
    from cilium_tpu.datapath.engine import Datapath, make_full_batch6
    from cilium_tpu.datapath.events import (DROP_UNKNOWN_TARGET,
                                            ICMP6_ECHO_REPLY,
                                            ICMP6_NS_REPLY)
    from cilium_tpu.identity import RESERVED_WORLD
    from cilium_tpu.policy.mapstate import (EGRESS, PolicyKey,
                                            PolicyMapState,
                                            PolicyMapStateEntry)
    rng = np.random.default_rng(23)
    router = "f00d::1"
    idents = [800 + i for i in range(4)]
    prefixes = {f"2001:db8:{i + 1:x}::/64": ident
                for i, ident in enumerate(idents)}

    st = PolicyMapState()
    # half the identities may send ICMPv6 (egress proto-58 rows);
    # a couple of TCP rows make sure families don't cross-match
    for ident in idents[:2]:
        st[PolicyKey(identity=ident, dest_port=0, nexthdr=58,
                     direction=EGRESS)] = PolicyMapStateEntry()
    st[PolicyKey(identity=idents[2], dest_port=443, nexthdr=6,
                 direction=EGRESS)] = PolicyMapStateEntry()

    dp = Datapath(ct_slots=1 << 10, ct_probe=4)
    dp.load_policy([st], revision=1, ipcache_prefixes={})
    dp.load_ipcache6(prefixes)
    dp.set_router_ip6(router)

    flows = []   # (daddr, icmp_type, nd_target, kind)
    for k in range(60):
        dst_pick = list(prefixes)[rng.integers(0, len(prefixes))]
        dst = str(ipaddress.ip_network(dst_pick).network_address +
                  int(rng.integers(1, 999)))
        roll = rng.random()
        if roll < 0.2:
            flows.append((router, 135, router, "ns-router"))
        elif roll < 0.4:
            flows.append((dst, 135, dst, "ns-other"))
        elif roll < 0.6:
            flows.append((router, 128, "::", "echo-router"))
        else:
            flows.append((dst, 128, "::", "echo-peer"))

    batch = make_full_batch6(
        endpoint=[0] * len(flows),
        saddr=["2001:db8:ff::9"] * len(flows),
        daddr=[f[0] for f in flows],
        sport=[0] * len(flows), dport=[0] * len(flows),
        direction=[1] * len(flows),
        proto=[58] * len(flows),
        icmp_type=[f[1] for f in flows],
        nd_target=[f[2] for f in flows])
    verdict, event, identity, _n = dp.process6(batch, now=50)
    v, ev = np.asarray(verdict), np.asarray(event)
    ids = np.asarray(identity)
    from cilium_tpu.compiler.lpm import LPM_MISS, oracle_lpm
    for i, (dst, typ, _t, kind) in enumerate(flows):
        if kind == "ns-router":
            assert v[i] == 0 and ev[i] == ICMP6_NS_REPLY, (i, kind)
        elif kind == "ns-other":
            assert v[i] < 0 and ev[i] == DROP_UNKNOWN_TARGET, (i, kind)
        elif kind == "echo-router":
            assert v[i] == 0 and ev[i] == ICMP6_ECHO_REPLY, (i, kind)
        else:
            lid = oracle_lpm(prefixes, dst)
            want_id = RESERVED_WORLD if lid == LPM_MISS else lid
            assert ids[i] == want_id, (dst, ids[i], want_id)
            want_v = oracle_verdict(st, want_id, 0, 58, EGRESS)
            assert v[i] == want_v, \
                f"{kind} {dst} id={want_id} device {v[i]} " \
                f"oracle {want_v}"
