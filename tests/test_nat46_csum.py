"""NAT46 translation + incremental checksum updates.

Reference parity: bpf/lib/nat46.h ipv4_to_ipv6 (:242) / ipv6_to_ipv4
(:337) — v4 embedded under a /96 prefix and extracted back — and
bpf/lib/csum.h incremental L4 checksum fix-ups after NAT rewrites,
verified against a from-scratch ones-complement checksum.
"""

import random

import jax.numpy as jnp
import numpy as np

from cilium_tpu.compiler.lpm import ipv4_to_u32, ipv6_batch_words
from cilium_tpu.datapath.csum import (checksum16, csum_update_u16,
                                      csum_update_u32, nat_csum_fix)
from cilium_tpu.datapath.nat46 import (WK_PREFIX, nat46_roundtrip_ok,
                                       nat46_translate, nat64_translate)


def test_nat46_embeds_under_prefix():
    v4 = jnp.asarray(np.asarray(
        [ipv4_to_u32("10.0.0.1"), ipv4_to_u32("192.168.1.200")],
        np.uint32).view(np.int32))
    v6 = nat46_translate(v4)
    got = np.asarray(v6).astype(np.uint32)
    # 64:ff9b::/96 + the embedded v4 (RFC 6052 well-known prefix)
    want0 = ipv6_batch_words(["64:ff9b::10.0.0.1"])[0]
    want1 = ipv6_batch_words(["64:ff9b::192.168.1.200"])[0]
    assert got[0].tolist() == np.asarray([want0], np.int32)[0] \
        .view(np.uint32).tolist()
    assert got[1].tolist() == np.asarray([want1], np.int32)[0] \
        .view(np.uint32).tolist()


def test_nat64_extracts_and_rejects_foreign():
    addrs = jnp.asarray(ipv6_batch_words(
        ["64:ff9b::10.0.0.1", "2001:db8::5"]))
    v4, ok = nat64_translate(addrs)
    assert np.asarray(ok).tolist() == [True, False]
    assert np.asarray(v4).astype(np.uint32)[0] == ipv4_to_u32("10.0.0.1")


def test_nat46_roundtrip_fuzz():
    rng = np.random.default_rng(11)
    v4 = jnp.asarray(rng.integers(0, 2 ** 32, 512,
                                  dtype=np.uint32).view(np.int32))
    assert bool(np.asarray(nat46_roundtrip_ok(v4)).all())
    # custom prefix too
    pfx = (0x20010DB8, 0x1234, 0, 0)
    assert bool(np.asarray(nat46_roundtrip_ok(v4, pfx)).all())


# ------------------------------------------------------------- csum

def _scratch_csum(words):
    return int(np.asarray(checksum16(jnp.asarray(
        np.asarray([words], np.int32))))[0])


def test_incremental_u16_matches_from_scratch():
    rng = random.Random(3)
    for _ in range(100):
        words = [rng.getrandbits(16) for _ in range(8)]
        base = _scratch_csum(words)
        idx = rng.randrange(8)
        new = rng.getrandbits(16)
        updated = int(np.asarray(csum_update_u16(
            jnp.asarray(np.asarray([base], np.int32)),
            jnp.asarray(np.asarray([words[idx]], np.int32)),
            jnp.asarray(np.asarray([new], np.int32))))[0])
        words[idx] = new
        assert updated == _scratch_csum(words), (words, idx)


def test_incremental_u32_and_nat_fix():
    rng = random.Random(5)
    for _ in range(50):
        # pseudo-header-ish word list: [addr_hi, addr_lo, port, ...]
        words = [rng.getrandbits(16) for _ in range(10)]
        base = _scratch_csum(words)
        old_addr = (words[0] << 16) | words[1]
        old_port = words[2]
        new_addr = rng.getrandbits(32)
        new_port = rng.getrandbits(16)
        arr = lambda v: jnp.asarray(np.asarray([v], np.uint32)
                                    .view(np.int32))
        fixed = int(np.asarray(nat_csum_fix(
            arr(base), arr(old_addr), arr(new_addr),
            arr(old_port), arr(new_port)))[0])
        words[0], words[1] = (new_addr >> 16) & 0xFFFF, new_addr & 0xFFFF
        words[2] = new_port
        assert fixed == _scratch_csum(words)


def test_udp_mangled_zero():
    """Full BPF_F_MARK_MANGLED_0 semantics: an incoming v4 UDP
    checksum of 0 means 'not computed' and is left at 0 across NAT;
    a nonzero checksum whose updated value folds to 0 is sent as
    0xFFFF; TCP is untouched by either rule."""
    arr = lambda v: jnp.asarray(np.asarray([v], np.uint32)
                                .view(np.int32))
    # incoming 0 stays 0 even across a real rewrite
    out = nat_csum_fix(arr(0), arr(0x0A000001), arr(0x0A000002),
                       arr(80), arr(8080), udp=True)
    assert int(np.asarray(out)[0]) == 0
    # a nonzero checksum that folds to zero after the update is
    # mangled to 0xFFFF: identity rewrite of csum 0xFFFF keeps the
    # fold at ~(~0xFFFF + 0) = 0 -> mangled
    out = nat_csum_fix(arr(0xFFFF), arr(5), arr(5), arr(7), arr(7),
                       udp=True)
    assert int(np.asarray(out)[0]) == 0xFFFF
    # TCP (default): incremental math only, no mangling
    out = nat_csum_fix(arr(0), arr(0), arr(0), arr(0), arr(0))
    assert int(np.asarray(out)[0]) == 0
