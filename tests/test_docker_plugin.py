"""Docker libnetwork driver: the full docker-side lifecycle against a
live agent.

Mirrors the reference plugin's flow (plugins/cilium-docker/driver):
Activate -> pools -> RequestAddress -> CreateEndpoint -> Join ->
Leave -> ReleaseAddress, plus the error paths (duplicate endpoint,
missing address, unknown method).
"""

import json
import urllib.request

import pytest

from cilium_tpu.cli import Client
from cilium_tpu.daemon import Daemon
from cilium_tpu.daemon.daemon import DaemonConfig
from cilium_tpu.daemon.rest import APIServer
from cilium_tpu.docker_plugin import (LibnetworkDriver, PluginError,
                                      PluginServer, endpoint_id_for)


@pytest.fixture()
def agent():
    d = Daemon(config=DaemonConfig())
    srv = APIServer(d).start()
    yield d, srv
    d.shutdown()


def _post(base, method, body=None):
    req = urllib.request.Request(
        f"{base}/{method}", method="POST",
        data=json.dumps(body or {}).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, json.loads(resp.read() or b"{}")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def test_full_docker_lifecycle_over_http(agent):
    d, srv = agent
    driver = LibnetworkDriver(Client(srv.base_url), wait_tries=2)
    ps = PluginServer(driver).start()
    try:
        code, out = _post(ps.base_url, "Plugin.Activate")
        assert code == 200
        assert out["Implements"] == ["NetworkDriver", "IpamDriver"]

        code, out = _post(ps.base_url, "NetworkDriver.GetCapabilities")
        assert out == {"Scope": "local"}

        code, out = _post(ps.base_url, "IpamDriver.RequestPool",
                          {"V6": False})
        assert out["PoolID"] == "CiliumPoolv4"
        gw = out["Data"]["com.docker.network.gateway"]
        assert gw.endswith("/32") and gw.startswith("10.200.")

        code, addr = _post(ps.base_url, "IpamDriver.RequestAddress",
                           {"PoolID": "CiliumPoolv4"})
        assert code == 200 and addr["Address"].endswith("/32")
        ip = addr["Address"].split("/")[0]
        assert ip in d.ipam.allocated()

        eid = "dockerep-0011223344556677"
        code, out = _post(ps.base_url, "NetworkDriver.CreateEndpoint", {
            "NetworkID": "net-1", "EndpointID": eid,
            "Interface": {"Address": addr["Address"]}})
        assert code == 200, out
        ep = d.endpoints.lookup(endpoint_id_for(eid))
        assert ep is not None and ep.ipv4 == ip
        lbls = [str(l) for l in ep.labels]
        assert any("docker-endpoint" in l for l in lbls)

        # duplicate create fails like driver.go:305
        code, out = _post(ps.base_url, "NetworkDriver.CreateEndpoint", {
            "NetworkID": "net-1", "EndpointID": eid,
            "Interface": {"Address": addr["Address"]}})
        assert code == 400 and "exists" in out["Err"]

        code, join = _post(ps.base_url, "NetworkDriver.Join",
                           {"EndpointID": eid})
        assert code == 200
        assert join["InterfaceName"]["DstPrefix"] == "cilium"
        assert join["DisableGatewayService"] is True
        dests = [r["Destination"] for r in join["StaticRoutes"]]
        assert "0.0.0.0/0" in dests  # default route via the gateway

        code, _ = _post(ps.base_url, "NetworkDriver.Leave",
                        {"EndpointID": eid})
        assert code == 200
        assert d.endpoints.lookup(endpoint_id_for(eid)) is None

        code, _ = _post(ps.base_url, "IpamDriver.ReleaseAddress",
                        {"Address": ip})
        assert code == 200
        assert ip not in d.ipam.allocated()
    finally:
        ps.shutdown()


def test_error_paths(agent):
    d, srv = agent
    driver = LibnetworkDriver(Client(srv.base_url), wait_tries=2)
    # missing IPv4 address (the v4-first inversion of driver.go:291)
    with pytest.raises(PluginError):
        driver.handle("NetworkDriver.CreateEndpoint",
                      {"EndpointID": "x", "Interface": {}})
    # join of an unknown endpoint
    with pytest.raises(PluginError):
        driver.handle("NetworkDriver.Join", {"EndpointID": "nope"})
    # unknown method
    with pytest.raises(PluginError):
        driver.handle("NetworkDriver.Frobnicate", {})
    # leave is idempotent: unknown endpoint does not raise
    assert driver.handle("NetworkDriver.Leave",
                         {"EndpointID": "nope"}) == {}
    # v6 pool reflects the daemon's v6 alloc range
    pool = driver.handle("IpamDriver.RequestPool", {"V6": True})
    assert pool["PoolID"] == "CiliumPoolv6"
    assert pool["Pool"] == str(d.ipam6.network)


def test_ipam_rest_routes(agent):
    d, srv = agent
    c = Client(srv.base_url)
    out = c.post("/ipam", {"family": "ipv4", "owner": "test"})
    ip = out["address"]["ipv4"]
    assert ip in d.ipam.allocated()
    assert out["host-addressing"]["ipv4"]["ip"] == d.host_ipv4
    assert c.delete(f"/ipam/{ip}") == {"released": ip}
    # double release 404s
    with pytest.raises(SystemExit):
        c.delete(f"/ipam/{ip}")
    # v6 family allocates from the v6 pool
    out6 = c.post("/ipam", {"family": "ipv6"})
    assert ":" in out6["address"]["ipv6"]
    # addressing is visible in /config for plugin bootstrap
    conf = c.get("/config")
    assert conf["addressing"]["ipv4"]["alloc-range"] == \
        str(d.ipam.network)


def test_ipam_unknown_family_is_400(agent):
    d, srv = agent
    c = Client(srv.base_url)
    before = len(d.ipam)
    with pytest.raises(SystemExit) as exc:
        c.post("/ipam", {"family": "IPv6"})  # case-sensitive contract
    assert "400" in str(exc.value)
    assert len(d.ipam) == before  # nothing leaked from the v4 pool


def test_restore_reclaims_allocated_ips(tmp_path):
    """Review regression: after a restart, restored endpoints' IPs must
    be re-claimed in the host-scope allocator, or POST /ipam hands out
    an address already in use (daemon/state.go restore + AllocateIP)."""
    state = str(tmp_path / "state")
    d1 = Daemon(config=DaemonConfig(state_dir=state))
    ip = d1.ipam_allocate("ipv4")["address"]["ipv4"]
    d1.endpoint_create(77, ipv4=ip, labels=["k8s:app=web"])
    assert d1.wait_for_quiesce(10)
    d1.shutdown()

    d2 = Daemon(config=DaemonConfig(state_dir=state))
    assert d2.restore_endpoints() == 1
    fresh = d2.ipam_allocate("ipv4")["address"]["ipv4"]
    assert fresh != ip
    assert ip in d2.ipam.allocated()
    d2.shutdown()


def test_endpoint_create_claims_ip_in_ipam(agent):
    """Review regression: a CNI/REST-created endpoint's IP must be
    claimed in the host-scope allocator while it lives, and freed when
    the endpoint goes — without stealing docker-flow claims."""
    d, srv = agent
    # 10.200.0.2 is the allocator's first free address; create an
    # endpoint on it directly (the CNI ADD shape)
    d.endpoint_create(901, ipv4="10.200.0.2", labels=["k8s:a=b"])
    fresh = d.ipam_allocate("ipv4")["address"]["ipv4"]
    assert fresh != "10.200.0.2"
    # lifecycle release: delete frees the endpoint's own claim
    d.endpoint_delete(901)
    assert "10.200.0.2" not in d.ipam.allocated()
    # docker-flow claim ("docker" owner) is NOT freed by endpoint
    # delete; IpamDriver.ReleaseAddress remains responsible
    ip = d.ipam_allocate("ipv4", owner="docker")["address"]["ipv4"]
    d.endpoint_create(902, ipv4=ip, labels=["k8s:a=b"])
    d.endpoint_delete(902)
    assert ip in d.ipam.allocated()
    assert d.ipam_release(ip)


def test_endpoint_create_conflicting_ip_is_409(agent):
    """Review regression: a second endpoint on an IP another live
    endpoint holds must be rejected, not silently double-claimed."""
    d, srv = agent
    d.endpoint_create(911, ipv4="10.200.0.9", labels=["k8s:a=b"])
    from cilium_tpu.ipam import IPAMError
    with pytest.raises(IPAMError):
        d.endpoint_create(912, ipv4="10.200.0.9", labels=["k8s:a=b"])
    # and over REST it surfaces as 409, not a 500
    c = Client(srv.base_url)
    with pytest.raises(SystemExit) as exc:
        c.put("/endpoint/913", {"ipv4": "10.200.0.9", "labels": []})
    assert "409" in str(exc.value)
    # deleting the holder frees the address for reuse
    d.endpoint_delete(911)
    d.endpoint_create(914, ipv4="10.200.0.9", labels=["k8s:a=b"])


def test_pack_meta_lockstep():
    """The C++ packing used by vc_classify_batch must equal
    compiler/policy_tables.py pack_meta (like the vc_hash_mix
    lockstep)."""
    import numpy as np
    from cilium_tpu.compiler.policy_tables import pack_meta
    from cilium_tpu.native import load
    lib = load()
    rng = np.random.default_rng(3)
    for _ in range(200):
        dport = int(rng.integers(0, 1 << 16))
        proto = int(rng.integers(0, 256))
        dirn = int(rng.integers(0, 2))
        assert lib.vc_pack_meta(dport, proto, dirn) == \
            pack_meta(dport, proto, dirn)


def test_driver_waits_for_daemon():
    # daemon not running: bounded retries then a clear error
    with pytest.raises(PluginError):
        LibnetworkDriver(Client("http://127.0.0.1:1"), wait_tries=2,
                         wait_base_s=0.0)
