"""Identity model/allocator tests (mirrors reference pkg/identity tests)."""

import pytest

from cilium_tpu import identity as idpkg
from cilium_tpu.identity import (IdentityCache, LocalIdentityAllocator,
                                 MINIMAL_NUMERIC_IDENTITY, RESERVED_HOST,
                                 RESERVED_WORLD, get_reserved_id,
                                 is_reserved_identity,
                                 look_up_reserved_identity)
from cilium_tpu.labels import Labels


def test_reserved_numbering():
    # reference: pkg/identity/numericidentity.go:42-60
    assert RESERVED_HOST == 1
    assert RESERVED_WORLD == 2
    assert idpkg.RESERVED_UNMANAGED == 3
    assert idpkg.RESERVED_HEALTH == 4
    assert idpkg.RESERVED_INIT == 5
    assert get_reserved_id("host") == 1
    assert get_reserved_id("world") == 2
    assert get_reserved_id("nonexistent") == 0


def test_reserved_identity_lookup():
    ident = look_up_reserved_identity(RESERVED_HOST)
    assert ident is not None
    assert ident.label_array.has("reserved.host")


def test_is_reserved():
    assert is_reserved_identity(1)
    assert is_reserved_identity(255)
    assert not is_reserved_identity(0)
    assert not is_reserved_identity(256)


def test_allocate_same_labels_same_id():
    a = LocalIdentityAllocator()
    l1 = Labels.from_model(["k8s:app=foo", "k8s:env=prod"])
    l2 = Labels.from_model(["k8s:env=prod", "k8s:app=foo"])
    id1, new1 = a.allocate(l1)
    id2, new2 = a.allocate(l2)
    assert new1 and not new2
    assert id1.id == id2.id
    assert id1.id >= MINIMAL_NUMERIC_IDENTITY


def test_allocate_different_labels_different_id():
    a = LocalIdentityAllocator()
    id1, _ = a.allocate(Labels.from_model(["k8s:app=foo"]))
    id2, _ = a.allocate(Labels.from_model(["k8s:app=bar"]))
    assert id1.id != id2.id


def test_release_refcount():
    a = LocalIdentityAllocator()
    labels = Labels.from_model(["k8s:app=foo"])
    ident, _ = a.allocate(labels)
    a.allocate(labels)  # refcount 2
    assert not a.release(ident)  # still referenced
    assert a.lookup_by_id(ident.id) is not None
    assert a.release(ident)  # freed
    assert a.lookup_by_id(ident.id) is None


def test_reserved_labels_shortcircuit():
    a = LocalIdentityAllocator()
    ident, new = a.allocate(Labels.from_model(["reserved:host"]))
    assert ident.id == RESERVED_HOST
    assert not new


def test_cluster_id_bits():
    # reference: identity/allocator.go:93 — cluster ID above bit 16
    a = LocalIdentityAllocator(cluster_id=3)
    ident, _ = a.allocate(Labels.from_model(["k8s:app=foo"]))
    assert ident.id >> 16 == 3
    assert ident.id & 0xFFFF >= MINIMAL_NUMERIC_IDENTITY


def test_identity_cache_snapshot():
    a = LocalIdentityAllocator()
    ident, _ = a.allocate(Labels.from_model(["k8s:app=foo"]))
    cache = IdentityCache.snapshot(a)
    assert ident.id in cache
    assert 1 in cache  # reserved host present
    assert cache[ident.id].has("k8s.app")
