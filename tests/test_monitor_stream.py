"""Monitor cross-process fan-out + deadlock-detecting locks.

Reference parity:
  * monitor/main.go:81-119 — the node monitor fans decoded datapath
    events out to subscriber processes over a socket with lossy
    bounded per-subscriber queues; `cilium monitor` follows from a
    separate process;
  * pkg/lock/lock.go:21-40 — Mutex/RWMutex wrappers with deadlock
    detection: a wait past the detector timeout reports both stacks
    instead of hanging the agent forever.
"""

import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import cilium_tpu.utils.lock as lock_mod
from cilium_tpu.daemon import Daemon
from cilium_tpu.monitor import MonitorHub, MonitorServer, monitor_follow
from cilium_tpu.utils.lock import (Mutex, PotentialDeadlockError, RMutex,
                                   RWMutex)
from cilium_tpu.utils.option import DaemonConfig

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def _ingest(hub, codes):
    n = len(codes)
    hub.ingest_batch(np.asarray(codes, np.int32),
                     np.zeros(n, np.int32),
                     np.full(n, 777, np.int32),
                     np.full(n, 80, np.int32),
                     np.full(n, 6, np.int32),
                     np.full(n, 100, np.int32))


# ----------------------------------------------------- stream in-proc

def test_monitor_stream_replay_and_follow():
    hub = MonitorHub()
    _ingest(hub, [0, -130])  # one trace, one drop (ringed)
    server = MonitorServer(hub, port=0).start()
    got = []
    done = threading.Event()

    def consume():
        for e in monitor_follow(server.port, replay=100):
            got.append(e)
            if len(got) >= 4:
                done.set()
                return

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    time.sleep(0.3)          # subscriber registered
    _ingest(hub, [0, -133])  # live events after subscribe
    assert done.wait(10), got
    codes = [e["code"] for e in got]
    assert set(codes[:2]) == {0, -130}   # ring replay (drops first)
    assert set(codes[2:]) == {0, -133}   # live follow
    assert all("message" in e for e in got)
    server.shutdown()


def test_monitor_stream_drops_only():
    hub = MonitorHub()
    server = MonitorServer(hub, port=0).start()
    got = []
    done = threading.Event()

    def consume():
        for e in monitor_follow(server.port, drops_only=True):
            got.append(e)
            done.set()
            return

    threading.Thread(target=consume, daemon=True).start()
    time.sleep(0.3)
    _ingest(hub, [0, 0, 0])      # traces: filtered out
    _ingest(hub, [-130])         # drop: delivered
    assert done.wait(10)
    assert got[0]["code"] == -130
    server.shutdown()


# ------------------------------------------------- cli cross-process

def test_cli_monitor_follows_from_separate_process():
    """The VERDICT cycle: a REAL `cilium monitor --socket` process
    follows the agent's event stream (monitor/main.go:81-119)."""
    d = Daemon(config=DaemonConfig())
    server = d.serve_monitor()
    proc = subprocess.Popen(
        [sys.executable, "-m", "cilium_tpu.cli", "monitor",
         "--socket", f"127.0.0.1:{server.port}"],
        stdout=subprocess.PIPE, text=True, cwd=REPO,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    # in-process subscribers (the hubble observer) register at daemon
    # construction; the CLI's arrival is the count going ABOVE that
    base_subs = len(d.monitor._subscribers)
    try:
        # wait until the CLI's subscription is registered (its jax
        # import alone can take seconds)
        deadline = time.time() + 30
        while len(d.monitor._subscribers) <= base_subs and \
                time.time() < deadline:
            time.sleep(0.1)
        assert len(d.monitor._subscribers) > base_subs, \
            "CLI never subscribed"
        _ingest(d.monitor, [-130, 0])
        lines = [proc.stdout.readline(), proc.stdout.readline()]
        blob = "".join(lines)
        assert "DROP" in blob and "Policy denied" in blob, blob
        assert "TRACE" in blob, blob
    finally:
        proc.kill()
        d.shutdown()


# ------------------------------------------------ deadlock detection

@pytest.fixture()
def short_timeout():
    """Enable the lockdebug build-tag analog with a short detector."""
    old_t, old_d = lock_mod.DEADLOCK_TIMEOUT, lock_mod.DEBUG
    lock_mod.DEADLOCK_TIMEOUT = 0.5
    lock_mod.DEBUG = True
    yield
    lock_mod.DEADLOCK_TIMEOUT = old_t
    lock_mod.DEBUG = old_d


def test_mutex_normal_operation(short_timeout):
    m = Mutex("m")
    with m:
        assert m.locked()
    assert not m.locked()
    r = RMutex("r")
    with r:
        with r:  # reentrant
            pass


def test_mutex_factory_passthrough_when_lockdebug_off():
    """Default build: the factory hands back the raw C-level lock —
    the build-tag semantics, zero wrapper overhead on the hot path."""
    assert not lock_mod.DEBUG
    m = Mutex("m")
    assert isinstance(m, type(threading.Lock()))
    r = RMutex("r")
    assert isinstance(r, type(threading.RLock()))
    with m:
        pass
    with r:
        with r:
            pass


def test_mutex_deadlock_detection_reports_both_stacks(short_timeout):
    m = Mutex("test-lock")
    holder_ready = threading.Event()
    release = threading.Event()

    def holder():
        with m:
            holder_ready.set()
            release.wait(5)

    t = threading.Thread(target=holder, daemon=True, name="the-holder")
    t.start()
    holder_ready.wait(5)
    with pytest.raises(PotentialDeadlockError) as exc:
        m.acquire()
    msg = str(exc.value)
    assert "test-lock" in msg
    assert "waiter stack" in msg
    assert "the-holder" in msg  # who holds it
    release.set()


def test_rwmutex_nested_read_survives_waiting_writer(short_timeout):
    """A reentrant read while a writer waits must NOT deadlock: the
    inner read bypasses the writers_waiting gate (the writer is gated
    on this very thread finishing)."""
    rw = RWMutex("rw")
    in_read = threading.Event()
    writer_waiting = threading.Event()
    ok = threading.Event()

    def nested_reader():
        with rw.read_locked():
            in_read.set()
            writer_waiting.wait(5)
            time.sleep(0.1)  # writer is parked in acquire_write now
            with rw.read_locked():   # must not block
                ok.set()

    def writer():
        in_read.wait(5)
        writer_waiting.set()
        try:
            rw.acquire_write()
            rw.release_write()
        except PotentialDeadlockError:
            pass

    threading.Thread(target=nested_reader, daemon=True).start()
    threading.Thread(target=writer, daemon=True).start()
    assert ok.wait(5), "nested read deadlocked against waiting writer"


def test_rwmutex_readers_and_writer_preference(short_timeout):
    rw = RWMutex("rw")
    with rw.read_locked():
        with rw.read_locked():
            pass  # reentrant readers fine

    # writer deadlock detection: a stuck reader trips the detector
    stuck = threading.Event()

    def reader():
        rw.acquire_read()
        stuck.set()
        time.sleep(5)

    threading.Thread(target=reader, daemon=True).start()
    stuck.wait(5)
    with pytest.raises(PotentialDeadlockError):
        rw.acquire_write()


def test_daemon_structures_use_debug_locks(short_timeout):
    """Under lockdebug, the daemon's core structures get detecting
    locks from the factory (default build: raw locks, zero cost)."""
    from cilium_tpu.utils.lock import _DebugMutex, _DebugRMutex
    d = Daemon(config=DaemonConfig())
    try:
        assert isinstance(d._lock, _DebugRMutex)
        assert isinstance(d.datapath._lock, _DebugMutex)
        assert isinstance(d.table_mgr._lock, _DebugRMutex)
        assert isinstance(d.proxy._lock, _DebugRMutex)
    finally:
        d.shutdown()


def test_agent_and_l7_events_join_the_monitor_stream():
    """AgentNotify + LogRecordNotify analogs: agent lifecycle and L7
    access-log records appear in the same monitor stream as datapath
    samples (pkg/monitor agent events + pkg/proxy/logger)."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import time as _time
    from cilium_tpu.daemon import Daemon
    from cilium_tpu.utils.option import DaemonConfig
    from cilium_tpu.proxy import AccessLogEntry
    d = Daemon(config=DaemonConfig())
    try:
        # agent-start announced at boot
        agent_evs = d.monitor.tail(100, kind="agent")
        assert any("agent-start" in e.note for e in agent_evs)
        d.endpoint_create(61, ipv4="10.200.0.61", labels=["k8s:m=n"])
        assert d.wait_for_quiesce(10)
        agent_evs = d.monitor.tail(100, kind="agent")
        notes = [e.note for e in agent_evs]
        assert any("endpoint-created id=61" in n for n in notes)
        assert any("endpoint-regenerate-success id=61" in n
                   for n in notes)
        # policy update + delete emit agent events
        from cilium_tpu.policy.api import (EndpointSelector, IngressRule,
                                           Rule)
        from cilium_tpu.labels import LabelArray
        d.policy_add([Rule(endpoint_selector=EndpointSelector.parse("m=n"),
                           ingress=[IngressRule()],
                           labels=LabelArray.parse("p=1"))])
        d.policy_delete(LabelArray.parse("p=1"))
        notes = [e.note for e in d.monitor.tail(100, kind="agent")]
        assert any(n.startswith("policy-updated") for n in notes)
        assert any(n.startswith("policy-deleted") for n in notes)
        # an access-log record flows into the stream as an l7 event
        d.proxy.access_log.log(AccessLogEntry(
            timestamp=_time.time(), proxy_id="1:ingress:TCP:80",
            l7_protocol="http", verdict="denied",
            src_identity=1234, dst_identity=5678,
            info={"method": "GET", "path": "/secret"}))
        l7 = d.monitor.tail(10, kind="l7")
        assert l7 and "denied" in l7[-1].note and \
            l7[-1].identity == 1234
        # stats aggregate the notification families
        st = d.monitor.stats()
        assert st.get("l7:http:denied", {}).get("events") == 1
        assert "agent:endpoint-created" in st
        # endpoint delete emits too
        d.endpoint_delete(61)
        notes = [e.note for e in d.monitor.tail(100, kind="agent")]
        assert any("endpoint-deleted id=61" in n for n in notes)
    finally:
        d.shutdown()


def test_monitor_rest_kind_filters():
    """kind=agent/l7/datapath filter the REST stream; 'datapath' is
    the named sentinel for packet samples (review regression)."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from cilium_tpu.cli import Client
    from cilium_tpu.daemon import Daemon
    from cilium_tpu.daemon.rest import APIServer
    from cilium_tpu.utils.option import DaemonConfig
    d = Daemon(config=DaemonConfig())
    srv = APIServer(d).start()
    try:
        c = Client(srv.base_url)
        # one datapath sample + the boot agent event are both present
        d.monitor.ingest_batch(np.array([-130]), np.array([1]),
                               np.array([2]), np.array([80]),
                               np.array([6]), np.array([100]))
        mixed = c.get("/monitor?n=50")
        kinds = {e["kind"] for e in mixed}
        assert "" in kinds and "agent" in kinds
        only_dp = c.get("/monitor?n=50&kind=datapath")
        assert only_dp and all(e["kind"] == "" for e in only_dp)
        only_agent = c.get("/monitor?n=50&kind=agent")
        assert only_agent and all(e["kind"] == "agent"
                                  for e in only_agent)
    finally:
        d.shutdown()


def test_cli_monitor_type_filter(capsys):
    """cilium monitor --type agent|l7|datapath (monitor --type analog)."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from cilium_tpu.cli import main
    from cilium_tpu.daemon import Daemon
    from cilium_tpu.daemon.rest import APIServer
    from cilium_tpu.utils.option import DaemonConfig
    d = Daemon(config=DaemonConfig())
    srv = APIServer(d).start()
    try:
        d.endpoint_create(81, ipv4="10.200.0.81", labels=["k8s:q=r"])
        d.wait_for_quiesce(10)
        assert main(["--api", srv.base_url, "monitor",
                     "--type", "agent"]) == 0
        out = capsys.readouterr().out
        assert "AGENT" in out and "endpoint-created id=81" in out
        assert "TRACE" not in out and "DROP" not in out
    finally:
        d.shutdown()
