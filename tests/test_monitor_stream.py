"""Monitor cross-process fan-out + deadlock-detecting locks.

Reference parity:
  * monitor/main.go:81-119 — the node monitor fans decoded datapath
    events out to subscriber processes over a socket with lossy
    bounded per-subscriber queues; `cilium monitor` follows from a
    separate process;
  * pkg/lock/lock.go:21-40 — Mutex/RWMutex wrappers with deadlock
    detection: a wait past the detector timeout reports both stacks
    instead of hanging the agent forever.
"""

import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import cilium_tpu.utils.lock as lock_mod
from cilium_tpu.daemon import Daemon
from cilium_tpu.monitor import MonitorHub, MonitorServer, monitor_follow
from cilium_tpu.utils.lock import (Mutex, PotentialDeadlockError, RMutex,
                                   RWMutex)
from cilium_tpu.utils.option import DaemonConfig

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def _ingest(hub, codes):
    n = len(codes)
    hub.ingest_batch(np.asarray(codes, np.int32),
                     np.zeros(n, np.int32),
                     np.full(n, 777, np.int32),
                     np.full(n, 80, np.int32),
                     np.full(n, 6, np.int32),
                     np.full(n, 100, np.int32))


# ----------------------------------------------------- stream in-proc

def test_monitor_stream_replay_and_follow():
    hub = MonitorHub()
    _ingest(hub, [0, -130])  # one trace, one drop (ringed)
    server = MonitorServer(hub, port=0).start()
    got = []
    done = threading.Event()

    def consume():
        for e in monitor_follow(server.port, replay=100):
            got.append(e)
            if len(got) >= 4:
                done.set()
                return

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    time.sleep(0.3)          # subscriber registered
    _ingest(hub, [0, -133])  # live events after subscribe
    assert done.wait(10), got
    codes = [e["code"] for e in got]
    assert set(codes[:2]) == {0, -130}   # ring replay (drops first)
    assert set(codes[2:]) == {0, -133}   # live follow
    assert all("message" in e for e in got)
    server.shutdown()


def test_monitor_stream_drops_only():
    hub = MonitorHub()
    server = MonitorServer(hub, port=0).start()
    got = []
    done = threading.Event()

    def consume():
        for e in monitor_follow(server.port, drops_only=True):
            got.append(e)
            done.set()
            return

    threading.Thread(target=consume, daemon=True).start()
    time.sleep(0.3)
    _ingest(hub, [0, 0, 0])      # traces: filtered out
    _ingest(hub, [-130])         # drop: delivered
    assert done.wait(10)
    assert got[0]["code"] == -130
    server.shutdown()


# ------------------------------------------------- cli cross-process

def test_cli_monitor_follows_from_separate_process():
    """The VERDICT cycle: a REAL `cilium monitor --socket` process
    follows the agent's event stream (monitor/main.go:81-119)."""
    d = Daemon(config=DaemonConfig())
    server = d.serve_monitor()
    proc = subprocess.Popen(
        [sys.executable, "-m", "cilium_tpu.cli", "monitor",
         "--socket", f"127.0.0.1:{server.port}"],
        stdout=subprocess.PIPE, text=True, cwd=REPO,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    try:
        # wait until the CLI's subscription is registered (its jax
        # import alone can take seconds)
        deadline = time.time() + 30
        while not d.monitor._subscribers and time.time() < deadline:
            time.sleep(0.1)
        assert d.monitor._subscribers, "CLI never subscribed"
        _ingest(d.monitor, [-130, 0])
        lines = [proc.stdout.readline(), proc.stdout.readline()]
        blob = "".join(lines)
        assert "DROP" in blob and "Policy denied" in blob, blob
        assert "TRACE" in blob, blob
    finally:
        proc.kill()
        d.shutdown()


# ------------------------------------------------ deadlock detection

@pytest.fixture()
def short_timeout():
    """Enable the lockdebug build-tag analog with a short detector."""
    old_t, old_d = lock_mod.DEADLOCK_TIMEOUT, lock_mod.DEBUG
    lock_mod.DEADLOCK_TIMEOUT = 0.5
    lock_mod.DEBUG = True
    yield
    lock_mod.DEADLOCK_TIMEOUT = old_t
    lock_mod.DEBUG = old_d


def test_mutex_normal_operation(short_timeout):
    m = Mutex("m")
    with m:
        assert m.locked()
    assert not m.locked()
    r = RMutex("r")
    with r:
        with r:  # reentrant
            pass


def test_mutex_factory_passthrough_when_lockdebug_off():
    """Default build: the factory hands back the raw C-level lock —
    the build-tag semantics, zero wrapper overhead on the hot path."""
    assert not lock_mod.DEBUG
    m = Mutex("m")
    assert isinstance(m, type(threading.Lock()))
    r = RMutex("r")
    assert isinstance(r, type(threading.RLock()))
    with m:
        pass
    with r:
        with r:
            pass


def test_mutex_deadlock_detection_reports_both_stacks(short_timeout):
    m = Mutex("test-lock")
    holder_ready = threading.Event()
    release = threading.Event()

    def holder():
        with m:
            holder_ready.set()
            release.wait(5)

    t = threading.Thread(target=holder, daemon=True, name="the-holder")
    t.start()
    holder_ready.wait(5)
    with pytest.raises(PotentialDeadlockError) as exc:
        m.acquire()
    msg = str(exc.value)
    assert "test-lock" in msg
    assert "waiter stack" in msg
    assert "the-holder" in msg  # who holds it
    release.set()


def test_rwmutex_nested_read_survives_waiting_writer(short_timeout):
    """A reentrant read while a writer waits must NOT deadlock: the
    inner read bypasses the writers_waiting gate (the writer is gated
    on this very thread finishing)."""
    rw = RWMutex("rw")
    in_read = threading.Event()
    writer_waiting = threading.Event()
    ok = threading.Event()

    def nested_reader():
        with rw.read_locked():
            in_read.set()
            writer_waiting.wait(5)
            time.sleep(0.1)  # writer is parked in acquire_write now
            with rw.read_locked():   # must not block
                ok.set()

    def writer():
        in_read.wait(5)
        writer_waiting.set()
        try:
            rw.acquire_write()
            rw.release_write()
        except PotentialDeadlockError:
            pass

    threading.Thread(target=nested_reader, daemon=True).start()
    threading.Thread(target=writer, daemon=True).start()
    assert ok.wait(5), "nested read deadlocked against waiting writer"


def test_rwmutex_readers_and_writer_preference(short_timeout):
    rw = RWMutex("rw")
    with rw.read_locked():
        with rw.read_locked():
            pass  # reentrant readers fine

    # writer deadlock detection: a stuck reader trips the detector
    stuck = threading.Event()

    def reader():
        rw.acquire_read()
        stuck.set()
        time.sleep(5)

    threading.Thread(target=reader, daemon=True).start()
    stuck.wait(5)
    with pytest.raises(PotentialDeadlockError):
        rw.acquire_write()


def test_daemon_structures_use_debug_locks(short_timeout):
    """Under lockdebug, the daemon's core structures get detecting
    locks from the factory (default build: raw locks, zero cost)."""
    from cilium_tpu.utils.lock import _DebugMutex, _DebugRMutex
    d = Daemon(config=DaemonConfig())
    try:
        assert isinstance(d._lock, _DebugRMutex)
        assert isinstance(d.datapath._lock, _DebugMutex)
        assert isinstance(d.table_mgr._lock, _DebugRMutex)
        assert isinstance(d.proxy._lock, _DebugRMutex)
    finally:
        d.shutdown()
