"""Native runtime tests: ring, verdict cache, struct alignment, and the
two-tier (host cache -> TPU batch) fast path.

Mirrors the reference's native-layer test posture: struct-ABI checks
(pkg/alignchecker), map semantics (pkg/maps/policymap tests), and the
hash-lockstep invariant between host and device tables.
"""

import threading

import numpy as np
import pytest

from cilium_tpu.compiler.hashtab import hash_mix
from cilium_tpu.compiler.policy_tables import pack_key
from cilium_tpu.native import (PKT_HEADER_DTYPE, PacketRing, VerdictCache,
                               check_struct_alignment, load)
from cilium_tpu.policy.mapstate import INGRESS, PolicyKey


def test_struct_alignment():
    check_struct_alignment()


def test_hash_lockstep_with_compiler():
    lib = load()
    rng = np.random.default_rng(0)
    a = rng.integers(0, 2 ** 32, 200, dtype=np.uint32)
    b = rng.integers(0, 2 ** 32, 200, dtype=np.uint32)
    host = hash_mix(a, b)
    native = np.array([lib.vc_hash_mix(int(x), int(y))
                       for x, y in zip(a, b)], np.uint32)
    np.testing.assert_array_equal(host, native)


def test_ring_roundtrip_soa():
    ring = PacketRing(capacity=1024)
    recs = np.zeros(100, PKT_HEADER_DTYPE)
    recs["endpoint"] = np.arange(100)
    recs["saddr"] = np.arange(100) + 1000
    recs["dport"] = 80
    recs["proto"] = 6
    recs["length"] = 512
    assert ring.push(recs) == 100
    assert len(ring) == 100
    out, n = ring.pop_batch(64)
    assert n == 64
    np.testing.assert_array_equal(out["endpoint"], np.arange(64))
    np.testing.assert_array_equal(out["saddr"], np.arange(64) + 1000)
    assert (out["dport"] == 80).all() and (out["proto"] == 6).all()
    out2, n2 = ring.pop_batch(64)
    assert n2 == 36
    np.testing.assert_array_equal(out2["endpoint"], np.arange(64, 100))
    assert len(ring) == 0
    ring.close()


def test_ring_overflow_counts_drops():
    ring = PacketRing(capacity=8)  # rounds to 8
    recs = np.zeros(20, PKT_HEADER_DTYPE)
    pushed = ring.push(recs)
    assert pushed == 8
    assert ring.dropped == 12
    ring.close()


def test_ring_spsc_threads():
    ring = PacketRing(capacity=1 << 12)
    total = 20_000
    got = []

    def producer():
        sent = 0
        while sent < total:
            n = min(512, total - sent)
            recs = np.zeros(n, PKT_HEADER_DTYPE)
            recs["endpoint"] = np.arange(sent, sent + n)
            pushed = ring.push(recs[:n], drop_on_full=False)
            sent += pushed

    def consumer():
        seen = 0
        while seen < total:
            out, n = ring.pop_batch(1024)
            if n:
                got.append(out["endpoint"].copy())
                seen += n

    t1 = threading.Thread(target=producer)
    t2 = threading.Thread(target=consumer)
    t1.start(); t2.start()
    t1.join(timeout=30); t2.join(timeout=30)
    all_ids = np.concatenate(got)
    assert len(all_ids) == total
    np.testing.assert_array_equal(all_ids, np.arange(total))
    assert ring.dropped == 0  # producer retried instead of dropping
    ring.close()


def test_verdict_cache_semantics():
    vc = VerdictCache(slots=16)
    ka, kb = pack_key(PolicyKey(identity=300, dest_port=80, nexthdr=6,
                                direction=INGRESS))
    assert vc.update(ka, kb, 0)
    assert vc.update(ka + 1, kb, 15001)
    assert len(vc) == 2
    values, found = vc.lookup_batch(
        np.array([ka, ka + 1, ka + 2], np.uint32),
        np.array([kb, kb, kb], np.uint32))
    assert found.tolist() == [True, True, False]
    assert values[0] == 0 and values[1] == 15001
    # update-in-place
    assert vc.update(ka, kb, 7)
    values, _ = vc.lookup_batch(np.array([ka], np.uint32),
                                np.array([kb], np.uint32))
    assert values[0] == 7
    # key_b == 0 is reserved (empty marker)
    assert not vc.update(1, 0, 1)
    # delete + miss
    assert vc.delete(ka, kb)
    assert not vc.delete(ka, kb)
    _, found = vc.lookup_batch(np.array([ka], np.uint32),
                               np.array([kb], np.uint32))
    assert not found[0]
    assert len(vc) == 1
    vc.flush()
    assert len(vc) == 0
    vc.close()


def test_verdict_cache_grows_and_backward_shift_delete():
    vc = VerdictCache(slots=8)
    rng = np.random.default_rng(3)
    keys = {}
    while len(keys) < 500:
        ka = int(rng.integers(0, 2 ** 32))
        kb = int(rng.integers(1, 2 ** 32))
        keys[(ka, kb)] = int(rng.integers(-1, 2 ** 15))
    for (ka, kb), v in keys.items():
        assert vc.update(ka, kb, v)
    assert len(vc) == 500
    assert vc.slots >= 1024  # grew past 0.5 load
    karr = np.array([k[0] for k in keys], np.uint32)
    kbrr = np.array([k[1] for k in keys], np.uint32)
    values, found = vc.lookup_batch(karr, kbrr)
    assert found.all()
    np.testing.assert_array_equal(values,
                                  np.array(list(keys.values()), np.int32))
    # delete half; survivors must all still be findable (backward-shift
    # correctness under long probe chains)
    items = list(keys.items())
    for (ka, kb), _ in items[:250]:
        assert vc.delete(ka, kb)
    survivors = items[250:]
    karr = np.array([k[0] for k, _ in survivors], np.uint32)
    kbrr = np.array([k[1] for k, _ in survivors], np.uint32)
    values, found = vc.lookup_batch(karr, kbrr)
    assert found.all()
    np.testing.assert_array_equal(
        values, np.array([v for _, v in survivors], np.int32))
    dead = np.array([k[0] for k, _ in items[:250]], np.uint32)
    deadb = np.array([k[1] for k, _ in items[:250]], np.uint32)
    _, found = vc.lookup_batch(dead, deadb)
    assert not found.any()
    vc.close()


def test_two_tier_fast_path_agrees_with_device():
    """Host cache hits must equal device verdicts for cached flows."""
    import jax.numpy as jnp
    from cilium_tpu.compiler.policy_tables import (compile_endpoints,
                                                   oracle_verdict)
    from cilium_tpu.ops.hashtab_ops import batched_lookup
    from cilium_tpu.policy.mapstate import (PolicyMapState,
                                            PolicyMapStateEntry)

    state = PolicyMapState()
    rng = np.random.default_rng(9)
    for _ in range(64):
        state[PolicyKey(identity=int(rng.integers(256, 1000)),
                        dest_port=int(rng.integers(1, 65536)), nexthdr=6,
                        direction=INGRESS)] = \
            PolicyMapStateEntry(proxy_port=int(rng.integers(0, 2) *
                                               15001))
    compiled = compile_endpoints([state], revision=1)

    # the control plane syncs the same entries into the host cache
    vc = VerdictCache()
    for k, v in state.items():
        ka, kb = pack_key(k)
        vc.update(ka, kb, v.proxy_port)

    keys = list(state.keys())
    ka = np.array([pack_key(k)[0] for k in keys], np.uint32)
    kb = np.array([pack_key(k)[1] for k in keys], np.uint32)
    host_vals, host_found = vc.lookup_batch(ka, kb)
    assert host_found.all()

    dev_found, dev_vals, _ = batched_lookup(
        jnp.asarray(compiled.key_id[0]), jnp.asarray(compiled.key_meta[0]),
        jnp.asarray(compiled.value[0]),
        jnp.asarray(ka.view(np.int32)), jnp.asarray(kb.view(np.int32)),
        compiled.max_probe)
    assert np.asarray(dev_found).all()
    np.testing.assert_array_equal(host_vals, np.asarray(dev_vals))
    for k, hv in zip(keys, host_vals):
        assert oracle_verdict(state, k.identity, k.dest_port, k.nexthdr,
                              k.direction) == hv
    vc.close()


def test_host_verdict_path_matches_oracle():
    """The host 3-stage path must agree with the scalar oracle on a
    randomized matrix (policygen-style)."""
    from cilium_tpu.compiler.policy_tables import oracle_verdict
    from cilium_tpu.native.fastpath import HostVerdictPath
    from cilium_tpu.policy.mapstate import (EGRESS, PolicyMapState,
                                            PolicyMapStateEntry)

    rng = np.random.default_rng(11)
    state = PolicyMapState()
    idents = list(rng.integers(256, 300, 12))
    ports = list(rng.integers(1, 1024, 12))
    for i in range(12):
        state[PolicyKey(identity=int(idents[i]), dest_port=int(ports[i]),
                        nexthdr=6, direction=INGRESS)] = \
            PolicyMapStateEntry(proxy_port=int(rng.integers(0, 2) * 12345))
    # some L3-only and L4-wildcard entries to exercise stages 2/3
    state[PolicyKey(identity=int(idents[0]),
                    direction=INGRESS)] = PolicyMapStateEntry()
    state[PolicyKey(identity=0, dest_port=443, nexthdr=6,
                    direction=INGRESS)] = PolicyMapStateEntry(
                        proxy_port=15001)

    hv = HostVerdictPath()
    hv.sync_endpoint(5, state)
    n = 512
    q_ident = rng.choice(np.array(idents + [9999, 0]), n).astype(np.uint32)
    q_port = rng.choice(np.array(ports + [443, 7]), n).astype(np.int32)
    q_proto = np.full(n, 6, np.int32)
    q_dir = np.zeros(n, np.int32)
    got = hv.classify(5, q_ident, q_port, q_proto, q_dir)
    for i in range(n):
        want = oracle_verdict(state, int(q_ident[i]), int(q_port[i]), 6,
                              0)
        assert got[i] == want, (i, q_ident[i], q_port[i], got[i], want)
    # unknown endpoint -> None; removed endpoint -> None
    assert hv.classify(6, q_ident, q_port, q_proto, q_dir) is None
    hv.remove_endpoint(5)
    assert hv.classify(5, q_ident, q_port, q_proto, q_dir) is None
    hv.close()
