"""Docker-events workload watcher over a real unix socket
(round-5 VERDICT #7).

The pluggable WorkloadWatcher proved the endpoint-lifecycle logic; this
proves the TRANSPORT: a Docker Engine API client speaking HTTP over
the dockerd unix socket against an in-repo fake dockerd — initial
container sync, streaming /events subscription, inspect-on-start,
die-cleanup, and reconnect-with-resync.  Reference:
pkg/workloads/docker.go EnableEventListener + processCreateWorkload.
"""

import json
import os
import socket
import socketserver
import threading
import time
from http.server import BaseHTTPRequestHandler

import pytest

from cilium_tpu.daemon import Daemon
from cilium_tpu.utils.option import DaemonConfig
from cilium_tpu.workloads import (DockerClient, DockerEventWatcher,
                                  WorkloadWatcher)


class _UnixHTTPServer(socketserver.ThreadingUnixStreamServer):
    daemon_threads = True
    allow_reuse_address = True


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    # AF_UNIX client addresses are empty strings; the base class logs
    # would explode on them
    def log_message(self, *args):
        pass

    def address_string(self):
        return "unix"

    def do_GET(self):  # noqa: N802 — http.server contract
        dockerd = self.server.dockerd
        if self.path.startswith("/events"):
            self._stream_events(dockerd)
            return
        if self.path.startswith("/containers/json"):
            with dockerd._cond:
                out = [
                    {"Id": cid, "Names": [f"/{c['name']}"],
                     "Labels": dict(c["labels"]), "State": "running"}
                    for cid, c in dockerd.containers.items()]
            self._json(200, out)
            return
        if self.path.startswith("/containers/"):
            if dockerd.fail_inspect:
                self._json(500, {"message": "dockerd overloaded"})
                return
            cid = self.path.split("/")[2]
            with dockerd._cond:
                c = dockerd.containers.get(cid)
            if c is None:
                self._json(404, {"message": "no such container"})
                return
            self._json(200, {"Id": cid, "Name": f"/{c['name']}",
                             "Config": {"Labels": dict(c["labels"])},
                             "State": {"Running": True}})
            return
        self._json(404, {"message": f"unknown path {self.path}"})

    def _stream_events(self, dockerd) -> None:
        with dockerd._cond:
            cursor = len(dockerd.events)
            epoch = dockerd.epoch
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        try:
            while True:
                with dockerd._cond:
                    while cursor >= len(dockerd.events) and \
                            dockerd.epoch == epoch:
                        dockerd._cond.wait(timeout=0.5)
                    if dockerd.epoch != epoch:
                        break
                    batch = dockerd.events[cursor:]
                    cursor = len(dockerd.events)
                for ev in batch:
                    data = (json.dumps(ev) + "\n").encode()
                    self.wfile.write(b"%x\r\n" % len(data) + data +
                                     b"\r\n")
                    self.wfile.flush()
            self.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        self.close_connection = True

    def _json(self, code: int, obj) -> None:
        data = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)


class FakeDockerd:
    """In-repo dockerd: container store + /events stream over a unix
    socket; start_container/stop_container are the test's hands."""

    def __init__(self, socket_path: str):
        self.socket_path = socket_path
        self._cond = threading.Condition()
        self.containers = {}
        self.events = []
        self.epoch = 0  # bump = drop live event streams
        self.fail_inspect = False  # 500 every /containers/{id}/json
        srv = _UnixHTTPServer(socket_path, _Handler)
        srv.dockerd = self
        self._srv = srv
        self._thread = threading.Thread(target=srv.serve_forever,
                                        daemon=True, name="fake-dockerd")

    def start(self) -> "FakeDockerd":
        self._thread.start()
        return self

    def shutdown(self) -> None:
        with self._cond:
            self.epoch += 1
            self._cond.notify_all()
        self._srv.shutdown()
        self._srv.server_close()
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass

    def start_container(self, cid: str, name: str, labels=None) -> None:
        with self._cond:
            self.containers[cid] = {"name": name,
                                    "labels": labels or {}}
            self.events.append({
                "Type": "container", "Action": "start",
                "Actor": {"ID": cid,
                          "Attributes": dict(labels or {})}})
            self._cond.notify_all()

    def stop_container(self, cid: str) -> None:
        with self._cond:
            self.containers.pop(cid, None)
            self.events.append({
                "Type": "container", "Action": "die",
                "Actor": {"ID": cid, "Attributes": {}}})
            self._cond.notify_all()

    def drop_streams(self) -> None:
        with self._cond:
            self.epoch += 1
            self._cond.notify_all()


@pytest.fixture()
def dockerd(tmp_path):
    d = FakeDockerd(str(tmp_path / "docker.sock")).start()
    yield d
    d.shutdown()


@pytest.fixture()
def daemon():
    d = Daemon(config=DaemonConfig(state_dir=""))
    yield d
    d.shutdown()


def _wait(fn, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(0.02)
    return fn()


def test_client_speaks_engine_api_over_unix_socket(dockerd):
    c = DockerClient(dockerd.socket_path)
    assert c.ping()
    dockerd.start_container("c1" * 32, "web", {"app": "web"})
    lst = c.list_containers()
    assert len(lst) == 1 and lst[0]["Labels"] == {"app": "web"}
    ins = c.inspect("c1" * 32)
    assert ins["Name"] == "/web"
    assert ins["Config"]["Labels"] == {"app": "web"}


def test_events_stream_start_die(dockerd):
    c = DockerClient(dockerd.socket_path)
    got = []

    def consume():
        for ev in c.events():
            got.append((ev["Action"], ev["Actor"]["ID"]))
            if len(got) >= 2:
                return

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    time.sleep(0.2)
    dockerd.start_container("aa" * 32, "a")
    dockerd.stop_container("aa" * 32)
    t.join(timeout=10)
    assert got == [("start", "aa" * 32), ("die", "aa" * 32)]


def test_container_lifecycle_drives_endpoints(dockerd, daemon):
    """The full round trip: docker start -> inspect -> endpoint with
    container labels + IPAM address; docker die -> endpoint gone and
    the address released."""
    sink = WorkloadWatcher(daemon, ipam=daemon.ipam)
    w = DockerEventWatcher(DockerClient(dockerd.socket_path),
                           sink).start()
    try:
        assert w.synced.wait(10)
        cid = "bb" * 32
        dockerd.start_container(cid, "web-1", {"app": "web"})
        assert _wait(lambda: sink.endpoint_of(cid) is not None)
        ep = daemon.endpoints.lookup(sink.endpoint_of(cid))
        assert ep is not None
        assert ep.ipv4, "endpoint should get an IPAM address"
        assert any("app=web" in str(l) for l in ep.labels.to_array())
        ip = ep.ipv4
        dockerd.stop_container(cid)
        assert _wait(lambda: sink.endpoint_of(cid) is None)
        assert _wait(lambda: daemon.endpoints.lookup(ep.id) is None)
        # the address is free again (release happens just after the
        # endpoint disappears — poll, don't race it)
        assert _wait(lambda: daemon.ipam.owner_of(ip) is None)
    finally:
        w.stop()


def test_initial_sync_adopts_preexisting_containers(dockerd, daemon):
    """Containers started while the agent was down are adopted by the
    list-then-watch startup (docker.go runtime sync)."""
    dockerd.start_container("cc" * 32, "old-1", {"app": "old"})
    sink = WorkloadWatcher(daemon, ipam=daemon.ipam)
    w = DockerEventWatcher(DockerClient(dockerd.socket_path),
                           sink).start()
    try:
        assert w.synced.wait(10)
        assert _wait(lambda: sink.endpoint_of("cc" * 32) is not None)
    finally:
        w.stop()


def test_stream_drop_resyncs_and_reaps_gap_deaths(dockerd, daemon):
    """A container dying while the event stream is down must still be
    cleaned up: reconnect re-lists and diffs (the reference re-syncs
    on EnableEventListener reconnect)."""
    sink = WorkloadWatcher(daemon, ipam=daemon.ipam)
    w = DockerEventWatcher(DockerClient(dockerd.socket_path),
                           sink).start()
    try:
        assert w.synced.wait(10)
        cid = "dd" * 32
        dockerd.start_container(cid, "doomed")
        assert _wait(lambda: sink.endpoint_of(cid) is not None)
        resyncs = w.resyncs
        # partition: stream drops AND the container dies silently
        with dockerd._cond:
            dockerd.containers.pop(cid, None)  # no event recorded
        dockerd.drop_streams()
        assert _wait(lambda: w.resyncs > resyncs)
        assert _wait(lambda: sink.endpoint_of(cid) is None), \
            "gap death must be reaped by the reconnect resync"
    finally:
        w.stop()


def test_inspect_failure_falls_back_to_event_attributes(dockerd,
                                                        daemon):
    """A transient inspect failure on a start event must not leave the
    container endpoint-less: the watcher falls back to the event's
    Actor.Attributes for name + labels (docker puts container labels
    there), and meta keys like 'image' don't leak into labels."""
    sink = WorkloadWatcher(daemon, ipam=daemon.ipam)
    w = DockerEventWatcher(DockerClient(dockerd.socket_path),
                           sink).start()
    try:
        assert w.synced.wait(10)
        dockerd.fail_inspect = True
        cid = "ee" * 32
        with dockerd._cond:
            dockerd.containers[cid] = {"name": "fb-1",
                                       "labels": {"app": "fb"}}
            dockerd.events.append({
                "Type": "container", "Action": "start",
                "Actor": {"ID": cid,
                          "Attributes": {"name": "fb-1",
                                         "image": "nginx:1",
                                         "app": "fb"}}})
            dockerd._cond.notify_all()
        assert _wait(lambda: sink.endpoint_of(cid) is not None), \
            "inspect failure left the container endpoint-less"
        ep = daemon.endpoints.lookup(sink.endpoint_of(cid))
        labels = [str(l) for l in ep.labels.to_array()]
        assert any("app=fb" in l for l in labels)
        assert not any("image" in l for l in labels), labels
        assert ep.container_name == "fb-1"
    finally:
        w.stop()


def test_watcher_stop_terminates_thread(dockerd, daemon):
    sink = WorkloadWatcher(daemon, ipam=daemon.ipam)
    w = DockerEventWatcher(DockerClient(dockerd.socket_path),
                           sink).start()
    assert w.synced.wait(10)
    w.stop()
    assert not w._thread.is_alive()
