"""End-to-end full-datapath tests: prefilter -> LB -> CT -> ipcache ->
policy -> CT-create, mirroring the reference's bpf_lxc.c packet walks
(SURVEY.md §3.3/3.4 call stacks)."""

import numpy as np
import jax.numpy as jnp

from cilium_tpu.compiler.lpm import ipv4_to_u32
from cilium_tpu.datapath.conntrack import CT_ESTABLISHED
from cilium_tpu.datapath.engine import Datapath, make_full_batch
from cilium_tpu.datapath.events import (DROP_POLICY, DROP_PREFILTER,
                                        TRACE_TO_LXC, TRACE_TO_PROXY)
from cilium_tpu.datapath.lb import Backend, Service
from cilium_tpu.datapath.verdict import VERDICT_ALLOW, VERDICT_DROP
from cilium_tpu.policy.mapstate import (EGRESS, INGRESS, PolicyKey,
                                        PolicyMapState, PolicyMapStateEntry)

CLIENT_ID = 2001
SERVER_ID = 2002


def build_dp():
    dp = Datapath(ct_slots=1 << 12)
    # Endpoint 0's policy: egress to SERVER_ID on 8080/TCP allowed;
    # L7 proxy on 9090/TCP via wildcard; everything else denied.
    st = PolicyMapState({
        PolicyKey(identity=SERVER_ID, dest_port=8080, nexthdr=6,
                  direction=EGRESS): PolicyMapStateEntry(),
        PolicyKey(identity=0, dest_port=9090, nexthdr=6,
                  direction=EGRESS): PolicyMapStateEntry(proxy_port=15001),
    })
    ipcache = {
        "10.1.0.0/16": CLIENT_ID,
        "10.2.0.0/16": SERVER_ID,
        "0.0.0.0/0": 2,  # world
    }
    dp.lb.upsert_service(Service(
        vip=ipv4_to_u32("10.96.0.10"), port=80,
        backends=[Backend(addr=ipv4_to_u32("10.2.0.5"), port=8080)]))
    dp.load_policy([st], revision=1, ipcache_prefixes=ipcache)
    return dp


def test_egress_allowed_via_service_vip():
    """Client hits the service VIP:80; LB DNATs to backend 8080 where
    egress policy allows SERVER_ID -> forwarded."""
    dp = build_dp()
    pkt = make_full_batch(
        endpoint=[0], saddr=[ipv4_to_u32("10.1.0.1")],
        daddr=[ipv4_to_u32("10.96.0.10")], sport=[40000], dport=[80])
    verdict, event, identity, nat = dp.process(pkt, now=100)
    assert int(verdict[0]) == VERDICT_ALLOW
    assert int(event[0]) == TRACE_TO_LXC
    assert int(identity[0]) == SERVER_ID  # post-DNAT dst identity
    assert dp.ct.entry_count() == 1       # CT entry created


def test_egress_denied_creates_no_ct():
    dp = build_dp()
    pkt = make_full_batch(
        endpoint=[0], saddr=[ipv4_to_u32("10.1.0.1")],
        daddr=[ipv4_to_u32("10.2.0.5")], sport=[40000], dport=[22])
    verdict, event, _, _ = dp.process(pkt, now=100)
    assert int(verdict[0]) == VERDICT_DROP
    assert int(event[0]) == DROP_POLICY
    assert dp.ct.entry_count() == 0


def test_established_bypasses_policy():
    """After the first allowed packet creates a CT entry, a policy swap
    to deny does not cut established flows (conntrack fast path)."""
    dp = build_dp()
    pkt = make_full_batch(
        endpoint=[0], saddr=[ipv4_to_u32("10.1.0.1")],
        daddr=[ipv4_to_u32("10.2.0.5")], sport=[40000], dport=[8080])
    v, _, _, _ = dp.process(pkt, now=100)
    assert int(v[0]) == VERDICT_ALLOW
    # swap in an empty (deny-all) policy; CT survives the swap
    dp.load_policy([PolicyMapState()], revision=2)
    v, _, _, _ = dp.process(pkt, now=101)
    assert int(v[0]) == VERDICT_ALLOW  # established
    # a new flow is now denied
    pkt2 = make_full_batch(
        endpoint=[0], saddr=[ipv4_to_u32("10.1.0.1")],
        daddr=[ipv4_to_u32("10.2.0.5")], sport=[40001], dport=[8080])
    v, _, _, _ = dp.process(pkt2, now=102)
    assert int(v[0]) == VERDICT_DROP


def test_proxy_redirect_verdict():
    dp = build_dp()
    pkt = make_full_batch(
        endpoint=[0], saddr=[ipv4_to_u32("10.1.0.1")],
        daddr=[ipv4_to_u32("10.2.0.5")], sport=[40000], dport=[9090])
    verdict, event, _, _ = dp.process(pkt, now=100)
    assert int(verdict[0]) == 15001
    assert int(event[0]) == TRACE_TO_PROXY


def test_prefilter_beats_everything():
    dp = build_dp()
    dp.prefilter.insert(["10.1.0.0/24"])
    dp.reload_prefilter()
    pkt = make_full_batch(
        endpoint=[0], saddr=[ipv4_to_u32("10.1.0.1")],
        daddr=[ipv4_to_u32("10.2.0.5")], sport=[40000], dport=[8080])
    verdict, event, _, _ = dp.process(pkt, now=100)
    assert int(verdict[0]) == VERDICT_DROP
    assert int(event[0]) == DROP_PREFILTER
    assert dp.ct.entry_count() == 0


def test_mixed_batch():
    dp = build_dp()
    c = ipv4_to_u32("10.1.0.1")
    s = ipv4_to_u32("10.2.0.5")
    vip = ipv4_to_u32("10.96.0.10")
    pkt = make_full_batch(
        endpoint=[0, 0, 0, 0],
        saddr=[c, c, c, c],
        daddr=[vip, s, s, s],
        sport=[40000, 40001, 40002, 40003],
        dport=[80, 8080, 22, 9090])
    verdict, event, _, _ = dp.process(pkt, now=100)
    v = np.asarray(verdict)
    assert v[0] == VERDICT_ALLOW    # via service
    assert v[1] == VERDICT_ALLOW    # direct allowed port
    assert v[2] == VERDICT_DROP     # denied port
    assert v[3] == 15001            # proxy
    assert dp.ct.entry_count() == 3  # dropped flow not created


def test_counters_accumulate():
    dp = build_dp()
    pkt = make_full_batch(
        endpoint=[0] * 8, saddr=[ipv4_to_u32("10.1.0.1")] * 8,
        daddr=[ipv4_to_u32("10.2.0.5")] * 8,
        sport=list(range(50000, 50008)), dport=[8080] * 8,
        length=[200] * 8)
    dp.process(pkt, now=100)
    assert int(np.asarray(dp.counters.packets).sum()) == 8
    assert int(np.asarray(dp.counters.bytes).sum()) == 8 * 200


# --- review regressions -----------------------------------------------------

def test_established_flow_keeps_proxy_redirect():
    """Every packet of a proxied flow must keep redirecting to the proxy
    port recorded in its CT entry, not just the first one (the reference
    stores proxy_port in ct_state)."""
    dp = build_dp()
    pkt = make_full_batch(
        endpoint=[0], saddr=[ipv4_to_u32("10.1.0.1")],
        daddr=[ipv4_to_u32("10.2.0.5")], sport=[40000], dport=[9090])
    v1, _, _, _ = dp.process(pkt, now=100)
    assert int(v1[0]) == 15001
    v2, e2, _, _ = dp.process(pkt, now=101)
    assert int(v2[0]) == 15001  # established, still redirected
    assert int(e2[0]) == TRACE_TO_PROXY


def test_prefilter_drop_does_not_touch_ct():
    """A denylisted source's spoofed RST must not tear down a live CT
    entry (update_mask gating)."""
    dp = build_dp()
    pkt = make_full_batch(
        endpoint=[0], saddr=[ipv4_to_u32("10.1.0.1")],
        daddr=[ipv4_to_u32("10.2.0.5")], sport=[40000], dport=[8080])
    v, _, _, _ = dp.process(pkt, now=100)
    assert int(v[0]) == VERDICT_ALLOW
    # now denylist the source and send an RST on the same tuple
    dp.prefilter.insert(["10.1.0.0/24"])
    dp.reload_prefilter()
    rst = make_full_batch(
        endpoint=[0], saddr=[ipv4_to_u32("10.1.0.1")],
        daddr=[ipv4_to_u32("10.2.0.5")], sport=[40000], dport=[8080],
        tcp_flags=[0x04])  # RST
    v, e, _, _ = dp.process(rst, now=101)
    assert int(v[0]) == VERDICT_DROP and int(e[0]) == DROP_PREFILTER
    # the entry is still alive well past the close timeout
    dp.prefilter.delete(["10.1.0.0/24"])
    dp.reload_prefilter()
    dp.load_policy([PolicyMapState()], revision=3)  # deny-all for new flows
    v, _, _, _ = dp.process(pkt, now=150)
    assert int(v[0]) == VERDICT_ALLOW  # still established


def test_reply_rev_nat_restores_vip():
    """A backend's reply gets its source rewritten back to the VIP via
    the rev-NAT index recorded at CT create."""
    dp = build_dp()
    vip = ipv4_to_u32("10.96.0.10")
    fwd = make_full_batch(
        endpoint=[0], saddr=[ipv4_to_u32("10.1.0.1")],
        daddr=[vip], sport=[40000], dport=[80])
    v, _, _, nat = dp.process(fwd, now=100)
    assert int(v[0]) == VERDICT_ALLOW
    assert np.asarray(nat.daddr).view(np.uint32)[0] == ipv4_to_u32("10.2.0.5")
    assert int(nat.dport[0]) == 8080
    # reply from the backend (ingress direction, reversed tuple)
    reply = make_full_batch(
        endpoint=[0], saddr=[ipv4_to_u32("10.2.0.5")],
        daddr=[ipv4_to_u32("10.1.0.1")], sport=[8080], dport=[40000],
        direction=[0], tcp_flags=[0x12])
    v, _, _, nat = dp.process(reply, now=101)
    assert int(v[0]) == VERDICT_ALLOW  # reply of established flow
    assert np.asarray(nat.saddr).view(np.uint32)[0] == vip
    assert int(nat.sport[0]) == 80


def test_lb_rev_nat_index_stable_across_delete():
    """Deleting one service must not renumber others' rev-NAT indices."""
    from cilium_tpu.datapath.lb import LoadBalancer
    lb = LoadBalancer()
    vip_a, vip_b = ipv4_to_u32("10.96.0.1"), ipv4_to_u32("10.96.0.2")
    lb.upsert_service(Service(vip=vip_a, port=80,
                              backends=[Backend(ipv4_to_u32("10.0.0.1"),
                                                8080)]))
    lb.upsert_service(Service(vip=vip_b, port=81,
                              backends=[Backend(ipv4_to_u32("10.0.0.2"),
                                                8081)]))
    idx_b = lb._services[(vip_b, 81, 6)].rev_nat_index
    lb.delete_service(vip_a, 80)
    assert lb._services[(vip_b, 81, 6)].rev_nat_index == idx_b
    # the rev table still maps idx_b -> vip_b
    saddr, sport = lb.rev_nat(
        jnp.asarray(np.asarray([0], np.int32)),
        jnp.asarray(np.asarray([1], np.int32)),
        jnp.asarray(np.asarray([idx_b], np.int32)))
    assert np.asarray(saddr).view(np.uint32)[0] == vip_b
    assert int(sport[0]) == 81
