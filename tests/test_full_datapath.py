"""End-to-end full-datapath tests: prefilter -> LB -> CT -> ipcache ->
policy -> CT-create, mirroring the reference's bpf_lxc.c packet walks
(SURVEY.md §3.3/3.4 call stacks)."""

import numpy as np
import jax.numpy as jnp

from cilium_tpu.compiler.lpm import ipv4_to_u32
from cilium_tpu.datapath.conntrack import CT_ESTABLISHED
from cilium_tpu.datapath.engine import Datapath, make_full_batch
from cilium_tpu.datapath.events import (DROP_POLICY, DROP_PREFILTER,
                                        TRACE_TO_LXC, TRACE_TO_PROXY)
from cilium_tpu.datapath.lb import Backend, Service
from cilium_tpu.datapath.verdict import VERDICT_ALLOW, VERDICT_DROP
from cilium_tpu.policy.mapstate import (EGRESS, INGRESS, PolicyKey,
                                        PolicyMapState, PolicyMapStateEntry)

CLIENT_ID = 2001
SERVER_ID = 2002


def build_dp():
    dp = Datapath(ct_slots=1 << 12)
    # Endpoint 0's policy: egress to SERVER_ID on 8080/TCP allowed;
    # L7 proxy on 9090/TCP via wildcard; everything else denied.
    st = PolicyMapState({
        PolicyKey(identity=SERVER_ID, dest_port=8080, nexthdr=6,
                  direction=EGRESS): PolicyMapStateEntry(),
        PolicyKey(identity=0, dest_port=9090, nexthdr=6,
                  direction=EGRESS): PolicyMapStateEntry(proxy_port=15001),
    })
    ipcache = {
        "10.1.0.0/16": CLIENT_ID,
        "10.2.0.0/16": SERVER_ID,
        "0.0.0.0/0": 2,  # world
    }
    dp.lb.upsert_service(Service(
        vip=ipv4_to_u32("10.96.0.10"), port=80,
        backends=[Backend(addr=ipv4_to_u32("10.2.0.5"), port=8080)]))
    dp.load_policy([st], revision=1, ipcache_prefixes=ipcache)
    return dp


def test_egress_allowed_via_service_vip():
    """Client hits the service VIP:80; LB DNATs to backend 8080 where
    egress policy allows SERVER_ID -> forwarded."""
    dp = build_dp()
    pkt = make_full_batch(
        endpoint=[0], saddr=[ipv4_to_u32("10.1.0.1")],
        daddr=[ipv4_to_u32("10.96.0.10")], sport=[40000], dport=[80])
    verdict, event, identity = dp.process(pkt, now=100)
    assert int(verdict[0]) == VERDICT_ALLOW
    assert int(event[0]) == TRACE_TO_LXC
    assert int(identity[0]) == SERVER_ID  # post-DNAT dst identity
    assert dp.ct.entry_count() == 1       # CT entry created


def test_egress_denied_creates_no_ct():
    dp = build_dp()
    pkt = make_full_batch(
        endpoint=[0], saddr=[ipv4_to_u32("10.1.0.1")],
        daddr=[ipv4_to_u32("10.2.0.5")], sport=[40000], dport=[22])
    verdict, event, _ = dp.process(pkt, now=100)
    assert int(verdict[0]) == VERDICT_DROP
    assert int(event[0]) == DROP_POLICY
    assert dp.ct.entry_count() == 0


def test_established_bypasses_policy():
    """After the first allowed packet creates a CT entry, a policy swap
    to deny does not cut established flows (conntrack fast path)."""
    dp = build_dp()
    pkt = make_full_batch(
        endpoint=[0], saddr=[ipv4_to_u32("10.1.0.1")],
        daddr=[ipv4_to_u32("10.2.0.5")], sport=[40000], dport=[8080])
    v, _, _ = dp.process(pkt, now=100)
    assert int(v[0]) == VERDICT_ALLOW
    # swap in an empty (deny-all) policy; CT survives the swap
    dp.load_policy([PolicyMapState()], revision=2)
    v, _, _ = dp.process(pkt, now=101)
    assert int(v[0]) == VERDICT_ALLOW  # established
    # a new flow is now denied
    pkt2 = make_full_batch(
        endpoint=[0], saddr=[ipv4_to_u32("10.1.0.1")],
        daddr=[ipv4_to_u32("10.2.0.5")], sport=[40001], dport=[8080])
    v, _, _ = dp.process(pkt2, now=102)
    assert int(v[0]) == VERDICT_DROP


def test_proxy_redirect_verdict():
    dp = build_dp()
    pkt = make_full_batch(
        endpoint=[0], saddr=[ipv4_to_u32("10.1.0.1")],
        daddr=[ipv4_to_u32("10.2.0.5")], sport=[40000], dport=[9090])
    verdict, event, _ = dp.process(pkt, now=100)
    assert int(verdict[0]) == 15001
    assert int(event[0]) == TRACE_TO_PROXY


def test_prefilter_beats_everything():
    dp = build_dp()
    dp.prefilter.insert(["10.1.0.0/24"])
    dp.reload_prefilter()
    pkt = make_full_batch(
        endpoint=[0], saddr=[ipv4_to_u32("10.1.0.1")],
        daddr=[ipv4_to_u32("10.2.0.5")], sport=[40000], dport=[8080])
    verdict, event, _ = dp.process(pkt, now=100)
    assert int(verdict[0]) == VERDICT_DROP
    assert int(event[0]) == DROP_PREFILTER
    assert dp.ct.entry_count() == 0


def test_mixed_batch():
    dp = build_dp()
    c = ipv4_to_u32("10.1.0.1")
    s = ipv4_to_u32("10.2.0.5")
    vip = ipv4_to_u32("10.96.0.10")
    pkt = make_full_batch(
        endpoint=[0, 0, 0, 0],
        saddr=[c, c, c, c],
        daddr=[vip, s, s, s],
        sport=[40000, 40001, 40002, 40003],
        dport=[80, 8080, 22, 9090])
    verdict, event, _ = dp.process(pkt, now=100)
    v = np.asarray(verdict)
    assert v[0] == VERDICT_ALLOW    # via service
    assert v[1] == VERDICT_ALLOW    # direct allowed port
    assert v[2] == VERDICT_DROP     # denied port
    assert v[3] == 15001            # proxy
    assert dp.ct.entry_count() == 3  # dropped flow not created


def test_counters_accumulate():
    dp = build_dp()
    pkt = make_full_batch(
        endpoint=[0] * 8, saddr=[ipv4_to_u32("10.1.0.1")] * 8,
        daddr=[ipv4_to_u32("10.2.0.5")] * 8,
        sport=list(range(50000, 50008)), dport=[8080] * 8,
        length=[200] * 8)
    dp.process(pkt, now=100)
    assert int(np.asarray(dp.counters.packets).sum()) == 8
    assert int(np.asarray(dp.counters.bytes).sum()) == 8 * 200
