"""Event-code lint: named by construction.

A static pass over ``datapath/events.py`` discovered via module
introspection (no hand-kept list): every DROP_*/TRACE_*/ICMP6_*/TIER_*
constant must have a human-readable name in its name table, the name
tables must not carry stale codes, and the Hubble verdict mapping
(``hubble/flow.verdict_of_event``) must classify every code.  Adding a
drop reason or trace point without naming it is a test failure, not a
review nit — `cilium-tpu monitor` and `hubble observe` render these
names instead of raw codes.
"""

import cilium_tpu.datapath.events as ev
from cilium_tpu.hubble.flow import (VERDICT_DROPPED, VERDICT_FORWARDED,
                                    VERDICT_REDIRECTED, verdict_of_event)


def _constants(*prefixes):
    """Module int constants by name prefix (introspected, not listed)."""
    return {name: val for name, val in vars(ev).items()
            if isinstance(val, int) and not isinstance(val, bool)
            and any(name.startswith(p) for p in prefixes)}


def test_every_drop_constant_is_named():
    drops = _constants("DROP_")
    unnamed = sorted(n for n, v in drops.items()
                     if v not in ev.DROP_NAMES)
    assert not unnamed, f"DROP_* constants missing from DROP_NAMES: " \
                        f"{unnamed}"


def test_every_trace_constant_is_named():
    # ICMP6_*_REPLY are trace-family terminal actions (the responder
    # answered); they render through TRACE_NAMES like the TRACE_TO_*s
    traces = _constants("TRACE_TO_", "ICMP6_")
    unnamed = sorted(n for n, v in traces.items()
                     if v not in ev.TRACE_NAMES)
    assert not unnamed, f"trace constants missing from TRACE_NAMES: " \
                        f"{unnamed}"


def test_every_tier_constant_is_named():
    tiers = _constants("TIER_")
    unnamed = sorted(n for n, v in tiers.items()
                     if v not in ev.TIER_NAMES)
    assert not unnamed, f"TIER_* constants missing from TIER_NAMES: " \
                        f"{unnamed}"


def test_name_tables_are_not_stale():
    drops = set(_constants("DROP_").values())
    traces = set(_constants("TRACE_TO_", "ICMP6_").values())
    tiers = set(_constants("TIER_").values())
    assert not set(ev.DROP_NAMES) - drops, \
        "DROP_NAMES carries codes with no DROP_* constant"
    assert not set(ev.TRACE_NAMES) - traces, \
        "TRACE_NAMES carries codes with no trace constant"
    assert not set(ev.TIER_NAMES) - tiers, \
        "TIER_NAMES carries codes with no TIER_* constant"


def test_no_code_collisions():
    drops = _constants("DROP_")
    traces = _constants("TRACE_TO_", "ICMP6_")
    assert len(set(drops.values())) == len(drops)
    assert len(set(traces.values())) == len(traces)
    assert not set(drops.values()) & set(traces.values())


def test_event_name_covers_every_code():
    for val in {**_constants("DROP_"),
                **_constants("TRACE_TO_", "ICMP6_")}.values():
        name = ev.event_name(val)
        assert name and not name.startswith("code "), val


def test_verdict_of_event_maps_every_code():
    """hubble/flow.verdict_of_event must classify every defined code:
    drops -> DROPPED, the proxy redirect -> REDIRECTED, every other
    forwarding/trace outcome -> FORWARDED."""
    for name, val in _constants("DROP_").items():
        assert verdict_of_event(val) == VERDICT_DROPPED, name
    for name, val in _constants("TRACE_TO_", "ICMP6_").items():
        expect = VERDICT_REDIRECTED if val == ev.TRACE_TO_PROXY \
            else VERDICT_FORWARDED
        assert verdict_of_event(val) == expect, name
