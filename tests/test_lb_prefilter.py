"""Load balancer + prefilter tests (bpf/lib/lb.h, bpf_xdp.c semantics)."""

import numpy as np
import jax.numpy as jnp

from cilium_tpu.compiler.lpm import ipv4_to_u32
from cilium_tpu.datapath.lb import Backend, LoadBalancer, Service
from cilium_tpu.datapath.prefilter import PreFilter


def as_i32(vals):
    return jnp.asarray(np.asarray(vals, np.uint32).view(np.int32))


def test_lb_dnat_and_distribution():
    lb = LoadBalancer()
    vip = ipv4_to_u32("10.96.0.1")
    backends = [Backend(addr=ipv4_to_u32(f"10.0.0.{i}"), port=8080)
                for i in range(1, 5)]
    lb.upsert_service(Service(vip=vip, port=80, backends=backends))

    n = 4096
    rng = np.random.default_rng(0)
    daddr = as_i32(np.full(n, vip, np.uint32))
    dport = jnp.asarray(np.full(n, 80, np.int32))
    proto = jnp.asarray(np.full(n, 6, np.int32))
    saddr = as_i32(rng.integers(0, 2**32, n, dtype=np.uint32))
    sport = jnp.asarray(rng.integers(1024, 65536, n, dtype=np.int32))

    new_daddr, new_dport, rev_nat, is_svc = lb.step(daddr, dport, proto,
                                                    saddr, sport)
    assert bool(is_svc.all())
    assert (np.asarray(new_dport) == 8080).all()
    # all outputs are backends; distribution roughly uniform
    chosen = np.asarray(new_daddr).view(np.uint32)
    allowed = {b.addr for b in backends}
    assert set(chosen.tolist()) <= allowed
    counts = np.bincount([list(sorted(allowed)).index(c) for c in chosen])
    assert counts.min() > n / len(allowed) * 0.7

    # same 5-tuple -> same backend (deterministic selection)
    nd2, _, _, _ = lb.step(daddr, dport, proto, saddr, sport)
    np.testing.assert_array_equal(np.asarray(new_daddr), np.asarray(nd2))


def test_lb_non_service_passthrough():
    lb = LoadBalancer()
    lb.upsert_service(Service(vip=ipv4_to_u32("10.96.0.1"), port=80,
                              backends=[Backend(ipv4_to_u32("10.0.0.1"),
                                                8080)]))
    daddr = as_i32([ipv4_to_u32("8.8.8.8")])
    nd, ndp, rn, is_svc = lb.step(daddr, jnp.asarray([80]),
                                  jnp.asarray([6]), daddr,
                                  jnp.asarray([1000]))
    assert not bool(is_svc.any())
    assert int(rn[0]) == 0
    np.testing.assert_array_equal(np.asarray(nd), np.asarray(daddr))


def test_lb_rev_nat_restores_vip():
    lb = LoadBalancer()
    vip = ipv4_to_u32("10.96.0.1")
    lb.upsert_service(Service(vip=vip, port=80,
                              backends=[Backend(ipv4_to_u32("10.0.0.1"),
                                                8080)]))
    # reply from backend: restore VIP using rev_nat index 1
    saddr, sport = lb.rev_nat(
        as_i32([ipv4_to_u32("10.0.0.1")]),
        jnp.asarray(np.asarray([8080], np.int32)),
        jnp.asarray(np.asarray([1], np.int32)))
    assert np.asarray(saddr).view(np.uint32)[0] == vip
    assert int(sport[0]) == 80


def test_lb_delete_service():
    lb = LoadBalancer()
    vip = ipv4_to_u32("10.96.0.1")
    lb.upsert_service(Service(vip=vip, port=80,
                              backends=[Backend(ipv4_to_u32("10.0.0.1"),
                                                8080)]))
    assert lb.delete_service(vip, 80)
    assert not lb.delete_service(vip, 80)
    _, _, _, is_svc = lb.step(as_i32([vip]), jnp.asarray([80]),
                              jnp.asarray([6]), as_i32([vip]),
                              jnp.asarray([1000]))
    assert not bool(is_svc.any())


def test_prefilter_drop_mask():
    pf = PreFilter()
    pf.insert(["203.0.113.0/24", "198.51.100.0/24"])
    addrs = as_i32([ipv4_to_u32("203.0.113.7"),
                    ipv4_to_u32("8.8.8.8"),
                    ipv4_to_u32("198.51.100.255")])
    mask = np.asarray(pf.drop_mask(addrs))
    np.testing.assert_array_equal(mask, [True, False, True])

    cidrs, rev = pf.dump()
    assert "203.0.113.0/24" in cidrs and rev >= 2

    pf.delete(["203.0.113.0/24"])
    mask = np.asarray(pf.drop_mask(addrs))
    np.testing.assert_array_equal(mask, [False, False, True])


def test_prefilter_delete_missing_raises():
    pf = PreFilter()
    pf.insert(["203.0.113.0/24"])
    try:
        pf.delete(["1.2.3.0/24"])
        assert False, "expected KeyError"
    except KeyError:
        pass
    # set unchanged after failed delete
    assert pf.dump()[0] == ["203.0.113.0/24"]


def test_prefilter_empty_no_drops():
    pf = PreFilter()
    mask = np.asarray(pf.drop_mask(as_i32([1, 2, 3])))
    assert not mask.any()
