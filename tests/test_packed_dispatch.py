"""Packed dispatch buffers (parallel/packing.py): the dispatch-floor
refactor's correctness contract.

- **Packed vs legacy parity** — the engine's grouped-buffer steps must
  be BIT-EXACT against the legacy pytree form (raw ``FullTables``
  leaves + per-leaf CT state + per-leaf counters) across seeds, for
  both families, with flow aggregation and provenance fused: verdicts,
  events, identities, NAT results, provenance pairs, and every piece
  of mutable state.  Only argument marshalling moved; the compiled
  math may not change.
- **Delta-apply write-through** — a single-rule policy update on the
  refresh_policy fast path lands in the packed policy slices as a row
  scatter (visible to the serving path) WITHOUT a full repack.
- **Donation** — the mutable-state packs (CT, counters) stay donated
  through the grouped step: inputs are invalidated after dispatch and
  the lowered HLO carries the buffer-aliasing annotations.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bench import build_config1
from cilium_tpu.datapath.conntrack import (CTState, ct_host_fields,
                                           make_ct_state)
from cilium_tpu.datapath.engine import Datapath, make_full_batch6
from cilium_tpu.datapath.pipeline import (PACKED_FIELDS,
                                          full_datapath_step6,
                                          full_datapath_step_packed)
from cilium_tpu.datapath.verdict import Counters
from cilium_tpu.policy.mapstate import (INGRESS, PolicyKey,
                                        PolicyMapState,
                                        PolicyMapStateEntry)


def _engine(n_endpoints=4, flows=True, provenance=True):
    states, prefixes = build_config1(n_rules=30,
                                     n_endpoints=n_endpoints)
    dp = Datapath(ct_slots=1 << 8)
    dp.telemetry_enabled = False
    if flows:
        # claim_every=1: every batch runs the claiming variant, so the
        # legacy twin (default claim budget) stays program-identical
        dp.enable_flow_aggregation(slots=1 << 7, claim_every=1)
    if provenance:
        dp.enable_provenance()
    dp.load_policy(states, revision=1, ipcache_prefixes=prefixes)
    for slot in range(n_endpoints):
        dp.set_endpoint_identity(slot, 1000 + slot)
    return dp


def _records(rng, n, n_endpoints):
    return {
        "endpoint": rng.integers(0, n_endpoints, n).astype(np.int32),
        "saddr": rng.integers(0, 1 << 32, n,
                              dtype=np.uint32).view(np.int32),
        "daddr": rng.integers(0, 1 << 32, n,
                              dtype=np.uint32).view(np.int32),
        "sport": rng.integers(1024, 64000, n).astype(np.int32),
        "dport": rng.integers(1, 65536, n).astype(np.int32),
        "proto": np.full(n, 6, np.int32),
        "direction": rng.integers(0, 2, n).astype(np.int32),
        "tcp_flags": np.full(n, 0x02, np.int32),
        "length": np.full(n, 256, np.int32),
        "is_fragment": np.zeros(n, np.int32),
    }


def _stage(recs, n):
    out = np.empty((len(PACKED_FIELDS), n), np.int32)
    for i, f in enumerate(PACKED_FIELDS):
        out[i] = recs[f][:n]
    return out


def _legacy_counters(dp):
    n = dp._counters.shape[1]
    return Counters(packets=jnp.zeros(n, jnp.uint32),
                    bytes=jnp.zeros(n, jnp.uint32))


def _assert_ct_equal(pack_state, legacy_state):
    packed = ct_host_fields(pack_state)
    legacy = ct_host_fields(legacy_state)
    for f in CTState._fields:
        np.testing.assert_array_equal(packed[f], legacy[f], err_msg=f)


@pytest.mark.parametrize("seed", [11, 12, 13])
def test_packed_vs_legacy_parity_v4(seed):
    """Engine (grouped buffers, packed CT/counters) vs the legacy
    pytree jit over the same tables: bit-exact outputs AND state,
    with flows + provenance fused, across batches that establish CT
    entries."""
    dp = _engine()
    legacy_step = jax.jit(functools.partial(full_datapath_step_packed,
                                            **dp._statics4),
                          donate_argnums=(1, 2))
    lct = make_ct_state(dp.ct.slots)
    lcnt = _legacy_counters(dp)
    from cilium_tpu.hubble.aggregation import make_flow_state
    lflows = make_flow_state(dp.flows.slots)
    rng = np.random.default_rng(seed)
    n_eps = 4
    recs = _records(rng, 96, n_eps)
    for i in range(3):
        # re-dispatch the same tuples on later rounds: established
        # flows must take the CT path identically on both legs
        stage = _stage(recs, 96)
        now = 1000 + i
        v, e, ident, nat = dp.process_packed(stage, now=now)
        prov = dp.last_provenance
        outs = legacy_step(dp._tables, lct, lcnt,
                           jnp.asarray(stage), jnp.int32(now), lflows)
        lv, le, li, lnat, lct, lcnt, lflows, lslot, ltier = outs
        np.testing.assert_array_equal(np.asarray(v), np.asarray(lv))
        np.testing.assert_array_equal(np.asarray(e), np.asarray(le))
        np.testing.assert_array_equal(np.asarray(ident),
                                      np.asarray(li))
        for a, b in zip(nat, lnat):
            np.testing.assert_array_equal(np.asarray(a),
                                          np.asarray(b))
        np.testing.assert_array_equal(np.asarray(prov.match_slot),
                                      np.asarray(lslot))
        np.testing.assert_array_equal(np.asarray(prov.tier),
                                      np.asarray(ltier))
    _assert_ct_equal(dp.ct.state, lct)
    np.testing.assert_array_equal(np.asarray(dp._counters[0]),
                                  np.asarray(lcnt.packets))
    np.testing.assert_array_equal(np.asarray(dp._counters[1]),
                                  np.asarray(lcnt.bytes))
    for a, b in zip(dp.flows.state, lflows):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("seed", [21, 22, 23])
def test_packed_vs_legacy_parity_v6(seed):
    """The v6 twin: grouped tables/state vs the legacy pytree leg,
    flows + provenance fused."""
    dp = _engine()
    legacy_step = jax.jit(functools.partial(full_datapath_step6,
                                            **dp._statics6),
                          donate_argnums=(1, 2))
    lct = make_ct_state(dp.ct6.slots)
    lcnt = _legacy_counters(dp)
    from cilium_tpu.hubble.aggregation import make_flow_state
    lflows = make_flow_state(dp.flows.slots)
    rng = np.random.default_rng(seed)
    n = 64
    words = rng.integers(0, 1 << 32, (n, 4),
                         dtype=np.uint32).view(np.int32)
    pkt = make_full_batch6(
        endpoint=rng.integers(0, 4, n), saddr=words,
        daddr=words[::-1].copy(),
        sport=rng.integers(1024, 64000, n),
        dport=rng.integers(1, 65536, n),
        direction=rng.integers(0, 2, n))
    for i in range(3):
        now = 2000 + i
        v, e, ident, nat = dp.process6(pkt, now=now)
        prov = dp.last_provenance
        outs = legacy_step(dp._tables6, lct, lcnt, pkt,
                           jnp.int32(now), lflows)
        lv, le, li, lnat, lct, lcnt, lflows, lslot, ltier = outs
        np.testing.assert_array_equal(np.asarray(v), np.asarray(lv))
        np.testing.assert_array_equal(np.asarray(e), np.asarray(le))
        np.testing.assert_array_equal(np.asarray(ident),
                                      np.asarray(li))
        for a, b in zip(nat, lnat):
            np.testing.assert_array_equal(np.asarray(a),
                                          np.asarray(b))
        np.testing.assert_array_equal(np.asarray(prov.match_slot),
                                      np.asarray(lslot))
        np.testing.assert_array_equal(np.asarray(prov.tier),
                                      np.asarray(ltier))
    _assert_ct_equal(dp.ct6.state, lct)
    np.testing.assert_array_equal(np.asarray(dp._counters[0]),
                                  np.asarray(lcnt.packets))


def test_delta_apply_writes_through_packed_slices():
    """A single-rule update on the refresh_policy fast path is a row
    scatter into the packed policy slices — verdict-visible through
    the packed dispatch path, with NO full repack."""
    from cilium_tpu.endpoint.tables import DeviceTableManager
    mgr = DeviceTableManager(initial_endpoints=4, initial_slots=64)
    for eid in (1, 2):
        mgr.attach(eid)
    dp = Datapath(ct_slots=1 << 8)
    dp.telemetry_enabled = False
    dp.use_table_manager(mgr, ipcache_prefixes={"10.0.0.0/8": 777})
    mgr.drain_dirty()  # discard attach-time zeros; rebuild packed all

    slot = mgr.slot_of(1)
    n = 16
    recs = {
        "endpoint": np.full(n, slot, np.int32),
        "saddr": np.full(n, (10 << 24) | 5, np.int32),  # 10.0.0.5
        "daddr": np.full(n, (10 << 24) | 9, np.int32),
        "sport": (40000 + np.arange(n)).astype(np.int32),
        "dport": np.full(n, 80, np.int32),
        "proto": np.full(n, 6, np.int32),
        "direction": np.zeros(n, np.int32),      # ingress
        "tcp_flags": np.full(n, 0x02, np.int32),
        "length": np.full(n, 100, np.int32),
        "is_fragment": np.zeros(n, np.int32),
    }
    v0, _e, _i, _n = dp.process_packed(_stage(recs, n), now=100)
    assert (np.asarray(v0) < 0).all()    # nothing installed: deny

    st = PolicyMapState()
    st[PolicyKey(identity=777, dest_port=80, nexthdr=6,
                 direction=INGRESS)] = PolicyMapStateEntry()
    out = mgr.sync_endpoint(1, st, revision=2)
    assert not out["full_swap"]
    packs_before = dp.pack_stats()["full-packs"]
    assert dp.refresh_policy(2) is False  # fast path: no re-jit
    stats = dp.pack_stats()
    assert stats["full-packs"] == packs_before, \
        "single-rule delta triggered a full repack"
    assert stats["row-writes"] >= 1

    # the packed slice now holds exactly the manager's row
    manifest = dp._manifest4
    h_id, h_meta, h_val = mgr.host_mirror()
    for path, mirror in (("datapath.key_id", h_id),
                         ("datapath.key_meta", h_meta),
                         ("datapath.value", h_val)):
        leaf = manifest.leaf(path)
        gidx = manifest.group_names().index(leaf.group)
        buf = np.asarray(dp._tbufs4[gidx])
        s = leaf.shape[1]
        got = buf[leaf.offset + slot * s:leaf.offset + (slot + 1) * s]
        np.testing.assert_array_equal(got, mirror[slot], err_msg=path)

    # and the new rule decides through the packed dispatch path
    v1, _e, ident, _n = dp.process_packed(_stage(recs, n), now=101)
    assert (np.asarray(v1) == 0).all()
    assert (np.asarray(ident) == 777).all()


def test_donation_survives_the_packed_dispatch():
    """The mutable-state packs stay donated: inputs invalidated after
    the step, aliasing annotated in the lowered HLO."""
    dp = _engine(flows=False, provenance=False)
    stage = np.zeros((10, 16), np.int32)
    dp.process_packed(stage, now=50)      # compile + settle
    ct_ref, cnt_ref = dp.ct.state, dp._counters
    v, _e, _i, _n = dp.process_packed(stage, now=51)
    np.asarray(v)                          # realize the batch
    for leaf in jax.tree_util.tree_leaves(ct_ref):
        assert leaf.is_deleted(), "CT pack was not donated"
    assert cnt_ref.is_deleted(), "counter pack was not donated"
    txt = dp._step_packed.lower(
        *dp._lower_args_packed(jnp.asarray(stage))).as_text()
    assert "tf.aliasing_output" in txt or "jax.buffer_donor" in txt
    # the grouped table buffers are NOT donated (cached across steps)
    for buf in dp._tbufs4:
        assert not buf.is_deleted()


def test_packed_groups_match_raw_tables():
    """Slicing the group buffers back by the manifest reproduces every
    raw table leaf bit-for-bit (pack/unpack round trip)."""
    from cilium_tpu.parallel import packing
    dp = _engine(flows=False, provenance=False)
    for manifest, bufs, tables in (
            (dp._manifest4, dp._tbufs4, dp._tables),
            (dp._manifest6, dp._tbufs6, dp._tables6)):
        rebuilt = packing.unpacker(manifest)(bufs)
        raw = dict(packing._walk(tables))
        got = dict(packing._walk(rebuilt))
        assert set(raw) == set(got)
        for path in raw:
            np.testing.assert_array_equal(
                np.asarray(raw[path]), np.asarray(got[path]),
                err_msg=path)
