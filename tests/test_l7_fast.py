"""On-device L7 fast verdicts: the redirect-to-proxy-as-exception
contract.

- **Bit-exact vs the proxy engines** — for every request the fused
  fast-verdict stage decides (eligible program + decidable payload),
  allow/deny must equal the socket proxy's own engine decision
  (HTTPPolicyEngine.check_one / DNSPolicyEngine.allowed_one) over the
  SAME match string, across seeds and ragged lengths.  Overlong
  (window-truncated) and absent payloads must fall back to the exact
  redirect verdict — fail-to-redirect, never fail-open.
- **Ineligibility** — header-spanning HTTP rules, kafka, allow-all and
  custom parser types never classify as fast; their slots always
  redirect, payload or not.
- **Disabled-path byte identity** — an engine that enabled then
  disabled fast verdicts lowers the EXACT pre-fast program (HLO text
  equal to a never-enabled engine's).
- Serving-lane / verdict-service payload lanes, CT bypass for decided
  connections, delta-apply write-through of the per-slot
  classification, tier grammar + metric propagation.
"""

import numpy as np
import pytest

from cilium_tpu.datapath.engine import Datapath
from cilium_tpu.datapath.events import (DROP_POLICY_L7,
                                        TIER_L7_FAST_ALLOW,
                                        TIER_L7_FAST_DENY, TIER_NAMES)
from cilium_tpu.datapath.pipeline import PACKED_FIELDS
from cilium_tpu.datapath.verdict import VERDICT_DROP_L7
from cilium_tpu.l7.dns import DNSPolicyEngine
from cilium_tpu.l7.fast import (FAST_DNS, FAST_HTTP, FastProgramSpec,
                                build_fast_programs, classify,
                                classify_dns, classify_http,
                                dns_match_string, encode_payloads,
                                http_match_string)
from cilium_tpu.l7.http import HTTPPolicyEngine, HTTPRequest
from cilium_tpu.policy.api import FQDNSelector, PortRuleHTTP
from cilium_tpu.policy.mapstate import (EGRESS, INGRESS, PolicyKey,
                                        PolicyMapState,
                                        PolicyMapStateEntry)

HTTP_PORT, DNS_PORT = 15001, 15002
HTTP_ID, DNS_ID = 777, 888
WINDOW = 128

HTTP_RULES = [PortRuleHTTP(method="GET", path="/public/.*"),
              PortRuleHTTP(method="GET", path="/api/v[0-9]+/users/.*"),
              PortRuleHTTP(method="POST", path="/api/v[0-9]+/orders"),
              PortRuleHTTP(method="PUT", path="/admin/.*",
                           host="admin\\.example\\.com")]
DNS_SELECTORS = [FQDNSelector(match_pattern="*.example.com"),
                 FQDNSelector(match_name="api.internal.svc"),
                 FQDNSelector(match_pattern="db-*.prod.local")]

PATHS = ["/public/idx.html", "/api/v2/users/42", "/api/v2/orders",
         "/secret/x", "/admin/panel", "/api/vX/users/1", "/", ""]
METHODS = ["GET", "POST", "PUT", "DELETE"]
HOSTS = ["", "admin.example.com", "other.example.com"]
NAMES = ["host1.example.com", "api.internal.svc", "db-3.prod.local",
         "evil.attacker.net", "example.com", "x.y.example.com",
         "db-.prod.local", "API.Internal.SVC."]


def _programs(window=WINDOW):
    return build_fast_programs(
        [FastProgramSpec(port=HTTP_PORT, protocol=FAST_HTTP,
                         patterns=tuple(classify_http(HTTP_RULES))),
         FastProgramSpec(port=DNS_PORT, protocol=FAST_DNS,
                         patterns=tuple(classify_dns(DNS_SELECTORS)))],
        window=window)


def _policy():
    st = PolicyMapState()
    st[PolicyKey(identity=HTTP_ID, dest_port=80, nexthdr=6,
                 direction=INGRESS)] = \
        PolicyMapStateEntry(proxy_port=HTTP_PORT)
    st[PolicyKey(identity=DNS_ID, dest_port=53, nexthdr=17,
                 direction=EGRESS)] = \
        PolicyMapStateEntry(proxy_port=DNS_PORT)
    # a redirect with NO fast program (stands in for kafka/header
    # rules): must always answer its proxy port
    st[PolicyKey(identity=999, dest_port=9092, nexthdr=6,
                 direction=INGRESS)] = \
        PolicyMapStateEntry(proxy_port=15999)
    st[PolicyKey(identity=555, dest_port=22, nexthdr=6,
                 direction=INGRESS)] = PolicyMapStateEntry()
    return st


def _engine(provenance=True, l7=True, window=WINDOW, ct_slots=1 << 8):
    dp = Datapath(ct_slots=ct_slots)
    dp.telemetry_enabled = False
    if provenance:
        dp.enable_provenance()
    if l7:
        dp.enable_l7_fast(_programs(window))
    dp.load_policy([_policy()], revision=1,
                   ipcache_prefixes={"10.0.0.0/8": HTTP_ID})
    return dp


def _stage(n, *, ident, dport, proto, direction, sport0=40000):
    recs = {
        "endpoint": np.zeros(n, np.int32),
        "saddr": np.full(n, (10 << 24) | 5, np.int32),
        "daddr": np.full(n, (10 << 24) | 9, np.int32),
        "sport": (sport0 + np.arange(n)).astype(np.int32),
        "dport": np.full(n, dport, np.int32),
        "proto": np.full(n, proto, np.int32),
        "direction": np.full(n, direction, np.int32),
        "tcp_flags": np.full(n, 0x02, np.int32),
        "length": np.full(n, 100, np.int32),
        "is_fragment": np.zeros(n, np.int32),
    }
    out = np.empty((len(PACKED_FIELDS), n), np.int32)
    for i, f in enumerate(PACKED_FIELDS):
        out[i] = recs[f]
    return out, recs


# The packet identity is resolved from the ipcache (10/8 -> HTTP_ID);
# for DNS/other slots we stamp the identity via mark_identity-style
# direct batches instead — simplest is to use the proxy-mark field of
# the full batch.  For packed-stage tests we route by dport/proto and
# give each slot its own ipcache identity via distinct saddrs.

def _engine_multi_ident():
    dp = Datapath(ct_slots=1 << 10)
    dp.telemetry_enabled = False
    dp.enable_provenance()
    dp.enable_l7_fast(_programs())
    dp.load_policy([_policy()], revision=1, ipcache_prefixes={
        "10.0.0.0/8": HTTP_ID,     # ingress peer = saddr
        "20.0.0.0/8": DNS_ID,      # egress peer = daddr
        "30.0.0.0/8": 999})
    return dp


@pytest.mark.parametrize("seed", [101, 102, 103])
def test_fast_verdicts_bit_exact_vs_proxy_engines(seed):
    """Every request the fast stage decides must match the socket
    proxy's engine verdict; truncated/absent payloads answer the
    exact redirect port."""
    rng = np.random.default_rng(seed)
    dp = _engine_multi_ident()
    http_eng = HTTPPolicyEngine(HTTP_RULES)
    dns_eng = DNSPolicyEngine(DNS_SELECTORS)
    n = 96
    # half HTTP (ingress, saddr in 10/8), half DNS (egress, daddr 20/8)
    is_http = rng.random(n) < 0.5
    strings, oracle, kinds = [], [], []
    reqs = []
    for i in range(n):
        if is_http[i]:
            req = HTTPRequest(
                method=METHODS[rng.integers(0, len(METHODS))],
                path=PATHS[rng.integers(0, len(PATHS))],
                host=HOSTS[rng.integers(0, len(HOSTS))])
            reqs.append(req)
            strings.append(http_match_string(req.method, req.path,
                                             req.host))
            oracle.append(bool(http_eng.check_one(req)))
            kinds.append("http")
        else:
            name = NAMES[rng.integers(0, len(NAMES))]
            reqs.append(name)
            strings.append(dns_match_string(name))
            oracle.append(bool(dns_eng.allowed_one(name)))
            kinds.append("dns")
    # sprinkle absent + truncated payloads: those must redirect
    absent = rng.random(n) < 0.15
    overlong = (~absent) & (rng.random(n) < 0.15)
    for i in np.flatnonzero(absent):
        strings[i] = None
    for i in np.flatnonzero(overlong):
        strings[i] = strings[i] + "z" * WINDOW  # exceeds the window
    payload = encode_payloads(strings, WINDOW)

    recs = {
        "endpoint": np.zeros(n, np.int32),
        "saddr": np.where(is_http, (10 << 24) | 5,
                          (40 << 24) | 7).astype(np.int32),
        "daddr": np.where(is_http, (10 << 24) | 9,
                          (20 << 24) | 9).astype(np.int32),
        "sport": (41000 + np.arange(n)).astype(np.int32),
        "dport": np.where(is_http, 80, 53).astype(np.int32),
        "proto": np.where(is_http, 6, 17).astype(np.int32),
        "direction": np.where(is_http, 0, 1).astype(np.int32),
        "tcp_flags": np.full(n, 0x02, np.int32),
        "length": np.full(n, 100, np.int32),
        "is_fragment": np.zeros(n, np.int32),
    }
    stage = np.empty((len(PACKED_FIELDS), n), np.int32)
    for i, f in enumerate(PACKED_FIELDS):
        stage[i] = recs[f]

    v, e, ident, _nat = dp.process_packed(stage, now=100,
                                          payload=payload)
    v = np.asarray(v)
    tiers = np.asarray(dp.last_provenance.tier)
    port_of = {"http": HTTP_PORT, "dns": DNS_PORT}
    for i in range(n):
        port = port_of[kinds[i]]
        if absent[i] or overlong[i]:
            assert v[i] == port, \
                (i, kinds[i], "undecidable payload must redirect")
            continue
        if oracle[i]:
            assert v[i] == 0, (i, kinds[i], reqs[i])
            assert tiers[i] == TIER_L7_FAST_ALLOW
        else:
            assert v[i] == VERDICT_DROP_L7, (i, kinds[i], reqs[i])
            assert tiers[i] == TIER_L7_FAST_DENY
            assert np.asarray(e)[i] == DROP_POLICY_L7


def test_decided_connections_never_reach_the_proxy_again():
    """A fast-allowed flow's CT entry records proxy port 0: every
    later packet of the connection follows the CT fast path as a
    plain allow — payload or not."""
    dp = _engine()
    n = 8
    stage, _ = _stage(n, ident=HTTP_ID, dport=80, proto=6, direction=0)
    strings = [http_match_string("GET", "/public/a")] * n
    payload = encode_payloads(strings, WINDOW)
    v1, _e, _i, _n = dp.process_packed(stage, now=100, payload=payload)
    assert (np.asarray(v1) == 0).all()
    # same tuples, NO payload: established flows keep their verdict
    v2, _e, _i, _n = dp.process_packed(stage, now=101)
    assert (np.asarray(v2) == 0).all()
    from cilium_tpu.datapath.events import TIER_CT_ESTABLISHED
    assert (np.asarray(dp.last_provenance.tier)
            == TIER_CT_ESTABLISHED).all()


def test_fast_denied_flows_create_no_ct_entry():
    dp = _engine()
    n = 4
    stage, _ = _stage(n, ident=HTTP_ID, dport=80, proto=6, direction=0)
    payload = encode_payloads(
        [http_match_string("GET", "/secret/x")] * n, WINDOW)
    before = dp.ct_entries()[0]
    v, _e, _i, _n = dp.process_packed(stage, now=100, payload=payload)
    assert (np.asarray(v) == VERDICT_DROP_L7).all()
    assert dp.ct_entries()[0] == before


def test_ineligible_rules_always_redirect():
    """Header-spanning HTTP rules, kafka, allow-all and custom parser
    types never classify; unclassified redirect slots answer their
    proxy port even when a payload is present."""
    assert classify_http([PortRuleHTTP(
        method="GET", path="/x", headers=("x-token secret",))]) is None
    assert classify_http([]) is None
    assert classify("kafka", [object()]) is None
    assert classify("memcached", None) is None
    assert classify("cassandra", None) is None
    # the 999 slot's port (15999) has no program: payload is ignored
    dp = _engine_multi_ident()
    n = 4
    recs = {
        "endpoint": np.zeros(n, np.int32),
        "saddr": np.full(n, (30 << 24) | 5, np.int32),  # ident 999
        "daddr": np.full(n, (10 << 24) | 9, np.int32),
        "sport": (42000 + np.arange(n)).astype(np.int32),
        "dport": np.full(n, 9092, np.int32),
        "proto": np.full(n, 6, np.int32),
        "direction": np.zeros(n, np.int32),
        "tcp_flags": np.full(n, 0x02, np.int32),
        "length": np.full(n, 100, np.int32),
        "is_fragment": np.zeros(n, np.int32),
    }
    stage = np.empty((len(PACKED_FIELDS), n), np.int32)
    for i, f in enumerate(PACKED_FIELDS):
        stage[i] = recs[f]
    payload = encode_payloads(["anything"] * n, WINDOW)
    v, _e, _i, _n = dp.process_packed(stage, now=100, payload=payload)
    assert (np.asarray(v) == 15999).all()
    from cilium_tpu.datapath.events import TIER_L7_REDIRECT
    assert (np.asarray(dp.last_provenance.tier) == TIER_L7_REDIRECT).all()


def test_disabled_path_is_byte_identical():
    """enable_l7_fast -> disable_l7_fast lowers the EXACT program a
    never-enabled engine lowers (HLO text equal), and the enabled
    program differs (sanity that the assertion can fail)."""
    import jax.numpy as jnp
    base = _engine(l7=False)
    toggled = _engine(l7=True)
    stage = jnp.asarray(np.zeros((10, 16), np.int32))
    enabled_txt = toggled._step_packed.lower(
        *toggled._lower_args_packed(stage)).as_text()
    toggled.disable_l7_fast()
    base_txt = base._step_packed.lower(
        *base._lower_args_packed(stage)).as_text()
    toggled_txt = toggled._step_packed.lower(
        *toggled._lower_args_packed(stage)).as_text()
    assert toggled_txt == base_txt
    assert enabled_txt != base_txt
    assert base.dispatch_leaf_counts() == \
        toggled.dispatch_leaf_counts()


def test_v6_family_fast_verdicts():
    """The v6 twin fast-decides from the shared policy tensors."""
    from cilium_tpu.datapath.engine import make_full_batch6
    dp = Datapath(ct_slots=1 << 8)
    dp.telemetry_enabled = False
    dp.enable_provenance()
    dp.enable_l7_fast(_programs())
    dp.load_policy([_policy()], revision=1)
    dp.load_ipcache6({"fd00::/16": HTTP_ID})
    n = 4
    pkt = make_full_batch6(
        endpoint=[0] * n, saddr=["fd00::5"] * n, daddr=["fd00::9"] * n,
        sport=[43000 + i for i in range(n)], dport=[80] * n,
        proto=[6] * n, direction=[0] * n)
    payload = encode_payloads(
        [http_match_string("GET", "/public/ok"),
         http_match_string("GET", "/secret/no"),
         None,
         http_match_string("POST", "/api/v1/orders")], WINDOW)
    v, e, _i, _nat = dp.process6(pkt, now=100, payload=payload)
    v = np.asarray(v)
    assert v[0] == 0
    assert v[1] == VERDICT_DROP_L7 and np.asarray(e)[1] == DROP_POLICY_L7
    assert v[2] == HTTP_PORT            # absent -> redirect
    assert v[3] == 0
    tiers = np.asarray(dp.last_provenance.tier)
    assert tiers[0] == TIER_L7_FAST_ALLOW
    assert tiers[1] == TIER_L7_FAST_DENY


def test_serving_lane_threads_the_payload():
    """submit_records(payload=...) reaches the fused stage through
    the shared continuous micro-batching dispatcher; payload-less
    submissions on the same lane keep redirecting."""
    dp = _engine(ct_slots=1 << 10)
    lane = dp.serving()
    n = 16
    _stage_unused, recs = _stage(n, ident=HTTP_ID, dport=80, proto=6,
                                 direction=0, sport0=44000)
    strings = [http_match_string("GET", "/public/a") if i % 2 == 0
               else http_match_string("GET", "/secret/b")
               for i in range(n)]
    payload = encode_payloads(strings, WINDOW)
    t1 = lane.submit_records(recs, n, payload=payload)
    v, _i = t1.result(timeout=30)
    assert t1.error is None
    assert (v[0::2] == 0).all()
    assert (v[1::2] == VERDICT_DROP_L7).all()
    # payload-less records on fresh tuples: the redirect stands
    _u, recs2 = _stage(n, ident=HTTP_ID, dport=80, proto=6,
                       direction=0, sport0=45000)
    t2 = lane.submit_records(recs2, n)
    v2, _i2 = t2.result(timeout=30)
    assert (v2 == HTTP_PORT).all()


def test_verdict_service_payload_frames():
    """The wire lane end to end: payload-carrying frames come back
    inline-decided, plain frames keep the redirect contract, and both
    interleave on one connection."""
    pytest.importorskip("cilium_tpu.native")
    from cilium_tpu.native import PKT_HEADER_DTYPE, load
    try:
        load()
    except (RuntimeError, OSError) as e:  # pragma: no cover
        pytest.skip(f"native runtime unavailable: {e}")
    from cilium_tpu.verdict_service import VerdictClient, VerdictService
    dp = _engine(ct_slots=1 << 12)
    svc = VerdictService(dp, max_batch=1 << 12).start()
    try:
        cli = VerdictClient("127.0.0.1", svc.port)
        n = 8
        recs = np.zeros(n, PKT_HEADER_DTYPE)
        recs["endpoint"] = 0
        recs["saddr"] = (10 << 24) | 5
        recs["daddr"] = (10 << 24) | 9
        recs["sport"] = 46000 + np.arange(n)
        recs["dport"] = 80
        recs["proto"] = 6
        recs["direction"] = 0
        recs["tcp_flags"] = 0x02
        recs["length"] = 100
        strings = [http_match_string("GET", "/public/a") if i % 2 == 0
                   else http_match_string("DELETE", "/secret")
                   for i in range(n)]
        from cilium_tpu.verdict_service import pack_wire_payloads
        v, _i = cli.classify(recs, payloads=pack_wire_payloads(
            strings, WINDOW))
        assert (v[0::2] == 0).all()
        assert (v[1::2] == VERDICT_DROP_L7).all()
        # a plain frame on the same connection: fresh tuples redirect
        recs2 = recs.copy()
        recs2["sport"] = 47000 + np.arange(n)
        v2, _i2 = cli.classify(recs2)
        assert (v2 == HTTP_PORT).all()
        cli.close()
    finally:
        svc.shutdown()


def test_delta_apply_l7_classification_write_through():
    """An L7 rule landing via the table-manager delta path classifies
    through the packed dispatch with NO full repack."""
    from cilium_tpu.endpoint.tables import DeviceTableManager
    mgr = DeviceTableManager(initial_endpoints=4, initial_slots=64)
    mgr.attach(1)
    dp = Datapath(ct_slots=1 << 8)
    dp.telemetry_enabled = False
    dp.enable_l7_fast(_programs())
    dp.use_table_manager(mgr, ipcache_prefixes={"10.0.0.0/8": HTTP_ID})
    mgr.drain_dirty()
    slot = mgr.slot_of(1)
    n = 4
    stage, _ = _stage(n, ident=HTTP_ID, dport=80, proto=6, direction=0)
    stage[0] = slot  # endpoint row
    payload = encode_payloads(
        [http_match_string("GET", "/public/a")] * n, WINDOW)
    v0, _e, _i, _n = dp.process_packed(stage, now=100, payload=payload)
    assert (np.asarray(v0) < 0).all()   # nothing installed yet
    st = PolicyMapState()
    st[PolicyKey(identity=HTTP_ID, dest_port=80, nexthdr=6,
                 direction=INGRESS)] = \
        PolicyMapStateEntry(proxy_port=HTTP_PORT)
    mgr.sync_endpoint(1, st, revision=2)
    packs_before = dp.pack_stats()["full-packs"]
    assert dp.refresh_policy(2) is False  # fast path
    assert dp.pack_stats()["full-packs"] == packs_before
    stage2 = stage.copy()
    stage2[3] = 48000 + np.arange(n)    # fresh sport: new flows
    v1, _e, _i, _n = dp.process_packed(stage2, now=101,
                                       payload=payload)
    assert (np.asarray(v1) == 0).all(), \
        "delta-applied L7 rule must fast-allow through the packs"


def test_tier_grammar_and_verdict_mapping():
    """FlowRecord tier grammar accepts the fast tiers; both outcomes'
    event codes map through verdict_of_event; format_rule renders the
    decided redirect entry."""
    from cilium_tpu.datapath.events import (TRACE_TO_LXC, format_rule)
    from cilium_tpu.hubble.filter import FlowFilter, parse_tier
    from cilium_tpu.hubble.flow import (VERDICT_DROPPED,
                                        VERDICT_FORWARDED,
                                        verdict_of_event)
    assert parse_tier("l7-fast-allow") == "l7-fast-allow"
    assert parse_tier("L7-FAST-DENY") == "l7-fast-deny"
    assert parse_tier(TIER_L7_FAST_ALLOW) == "l7-fast-allow"
    flt = FlowFilter.from_query({"tier": ["l7-fast-deny"]})
    assert flt.tier == "l7-fast-deny"
    assert TIER_NAMES[TIER_L7_FAST_ALLOW] == "l7-fast-allow"
    # the two outcomes' event codes
    assert verdict_of_event(DROP_POLICY_L7) == VERDICT_DROPPED
    assert verdict_of_event(TRACE_TO_LXC) == VERDICT_FORWARDED
    # the decided rule renders (the matched redirect entry keeps its
    # proxy-port attribution)
    s = format_rule({"identity": HTTP_ID, "dport": 80, "proto": 6,
                     "direction": 0, "proxy-port": HTTP_PORT})
    assert f"proxy={HTTP_PORT}" in s


def test_l7_fast_metric_propagation():
    """ingest_batch(tiers, match_slots, l7_proto_of) feeds
    l7_fast_verdicts_total{protocol,outcome} for exactly the
    fast-decided rows."""
    from cilium_tpu.monitor import MonitorHub
    from cilium_tpu.utils.metrics import L7_FAST_VERDICTS
    dp = _engine_multi_ident()
    n = 6
    stage, recs = _stage(n, ident=HTTP_ID, dport=80, proto=6,
                         direction=0, sport0=49000)
    strings = [http_match_string("GET", "/public/a"),
               http_match_string("GET", "/secret/x"),
               http_match_string("GET", "/public/b"),
               None, None, None]
    payload = encode_payloads(strings, WINDOW)
    v, e, ident, _nat = dp.process_packed(stage, now=100,
                                          payload=payload)
    prov = dp.last_provenance
    hub = MonitorHub()
    base_allow = L7_FAST_VERDICTS.value(
        labels={"protocol": "http", "outcome": "allow"})
    base_deny = L7_FAST_VERDICTS.value(
        labels={"protocol": "http", "outcome": "deny"})
    hub.ingest_batch(np.asarray(e), recs["endpoint"], np.asarray(ident),
                     recs["dport"], recs["proto"], recs["length"],
                     tiers=np.asarray(prov.tier),
                     match_slots=np.asarray(prov.match_slot),
                     rule_of=dp.provenance_rule_of(),
                     l7_proto_of=dp.l7_fast_protocol_of())
    assert L7_FAST_VERDICTS.value(
        labels={"protocol": "http", "outcome": "allow"}) - \
        base_allow == 2
    assert L7_FAST_VERDICTS.value(
        labels={"protocol": "http", "outcome": "deny"}) - \
        base_deny == 1
    # monitor samples carry the fast tier name
    fast = [s for s in hub.tail(50)
            if "l7-fast" in (TIER_NAMES.get(s.tier, ""))]
    assert fast, "no fast-tier samples ringed"
