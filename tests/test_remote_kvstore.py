"""Cross-process control plane over the TCP kvstore transport.

The round-1 gap this closes: every "distributed" protocol previously
ran inside one Python process.  Here the kvstore crosses real sockets
and real process boundaries:

- unit tier: RemoteBackend against a live KVStoreServer (ops, CAS,
  watches, locks, lease expiry) in-process but over TCP;
- agent tier: two full Daemon *subprocesses* allocate identities and
  converge ipcache through the server (reference: pkg/kvstore/etcd.go
  + allocator.go protocol);
- failure tier: kill -9 of an agent -> its lease lapses -> slave keys
  vanish and GC reclaims the identity (allocator.go:88-89).
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest
import threading

from cilium_tpu.kvstore.backend import (EVENT_CREATE, EVENT_DELETE,
                                        EVENT_LIST_DONE, KVLockError)
from cilium_tpu.kvstore.remote import RemoteBackend
from cilium_tpu.kvstore.server import KVStoreServer

AGENT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "agent_proc.py")


@pytest.fixture()
def server():
    srv = KVStoreServer(port=0, expire_interval=0.1).start()
    yield srv
    srv.shutdown()


@pytest.fixture()
def client(server):
    c = RemoteBackend(port=server.port, lease_ttl=5.0)
    yield c
    c.close()


# ------------------------------------------------------------- unit tier

def test_basic_ops_over_tcp(server, client):
    assert client.get("a") is None
    client.set("a", b"1")
    assert client.get("a") == b"1"
    client.set("dir/x", b"x")
    client.set("dir/y", b"y")
    assert client.list_prefix("dir/") == {"dir/x": b"x", "dir/y": b"y"}
    assert client.get_prefix("dir/") == b"x"
    client.delete("dir/x")
    assert client.list_prefix("dir/") == {"dir/y": b"y"}
    client.delete_prefix("dir/")
    assert client.list_prefix("dir/") == {}


def test_atomic_ops_over_tcp(server, client):
    assert client.create_only("k", b"v") is True
    assert client.create_only("k", b"w") is False
    assert client.get("k") == b"v"
    assert client.create_if_exists("k", "dep", b"d") is True
    assert client.create_if_exists("nope", "dep2", b"d") is False
    assert client.create_if_exists("k", "dep", b"again") is False


def test_watch_sees_other_clients_writes(server, client):
    other = RemoteBackend(port=server.port, lease_ttl=5.0)
    try:
        client.set("pre/existing", b"0")
        w = client.list_and_watch("pre/")
        ev = w.next_event(timeout=5)
        assert (ev.typ, ev.key) == (EVENT_CREATE, "pre/existing")
        assert w.next_event(timeout=5).typ == EVENT_LIST_DONE
        other.set("pre/live", b"1")
        ev = w.next_event(timeout=5)
        assert (ev.typ, ev.key, ev.value) == (EVENT_CREATE, "pre/live",
                                              b"1")
        other.delete("pre/live")
        ev = w.next_event(timeout=5)
        assert (ev.typ, ev.key) == (EVENT_DELETE, "pre/live")
        w.stop()
    finally:
        other.close()


def test_locks_exclude_across_clients(server, client):
    other = RemoteBackend(port=server.port, lease_ttl=5.0)
    try:
        lk = client.lock_path("locks/x", timeout=5)
        t0 = time.monotonic()
        with pytest.raises(KVLockError):
            other.lock_path("locks/x", timeout=0.4)
        assert time.monotonic() - t0 >= 0.35
        lk.unlock()
        other.lock_path("locks/x", timeout=5).unlock()
    finally:
        other.close()


def test_lease_expiry_after_disconnect(server):
    short = RemoteBackend(port=server.port, lease_ttl=0.5)
    watcher_client = RemoteBackend(port=server.port, lease_ttl=5.0)
    try:
        short.set("leased/gone", b"v", lease=True)
        short.set("plain/stays", b"v")
        w = watcher_client.watch("leased/")
        # hard disconnect: no clean close, keepalive stops
        short._closed.set()
        short._sock.close()
        ev = w.next_event(timeout=5)
        assert (ev.typ, ev.key) == (EVENT_DELETE, "leased/gone")
        assert watcher_client.get("leased/gone") is None
        assert watcher_client.get("plain/stays") == b"v"
        w.stop()
    finally:
        watcher_client.close()


def test_lease_survives_while_renewed(server):
    c = RemoteBackend(port=server.port, lease_ttl=0.6)
    try:
        c.set("alive/k", b"v", lease=True)
        time.sleep(1.5)  # > 2 TTLs; keepalive at ttl/3 keeps it alive
        assert c.get("alive/k") == b"v"
    finally:
        c.close()


# ------------------------------------------------------------ agent tier

def _spawn_agent(tmp_path, port, node, mode, ttl=2.0):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # stderr to a file: a full pipe buffer (JAX warnings) would block
    # the agent before it ever prints its report
    errfile = open(tmp_path / f"{node}.stderr", "w+")
    proc = subprocess.Popen(
        [sys.executable, AGENT, str(port), node, mode, str(ttl)],
        stdout=subprocess.PIPE, stderr=errfile, text=True, env=env)
    proc._errfile = errfile
    return proc


def _read_report(proc, timeout=90):
    out = {}

    def read():
        out["line"] = proc.stdout.readline()

    t = threading.Thread(target=read, daemon=True)
    t.start()
    t.join(timeout)
    line = out.get("line")
    if not line:
        proc.kill()
        proc._errfile.seek(0)
        raise AssertionError(
            f"no report within {timeout}s; stderr:\n"
            + proc._errfile.read()[-2000:])
    return json.loads(line)


def test_two_agent_processes_converge(server, tmp_path):
    """Two full Daemons in separate processes: same labels -> same
    identity ID, distinct labels -> distinct IDs, and each node's
    ipcache learns the other's endpoint IP through the server."""
    a = _spawn_agent(tmp_path, server.port, "node-a", "report")
    b = _spawn_agent(tmp_path, server.port, "node-b", "report")
    try:
        ra = _read_report(a)
        rb = _read_report(b)
        assert ra["shared_identity"] == rb["shared_identity"]
        assert ra["unique_identity"] != rb["unique_identity"]
        # ipcache converged both ways through the socket
        assert ra["ipcache"]["10.50.2.1"] == rb["shared_identity"]
        assert rb["ipcache"]["10.50.1.1"] == ra["shared_identity"]
        a.wait(timeout=60)
        b.wait(timeout=60)
    finally:
        for p in (a, b):
            if p.poll() is None:
                p.kill()


def test_kill9_agent_lease_reaped(server, tmp_path):
    """kill -9 models node death: the agent's slave keys vanish when
    its lease lapses and GC reclaims the masterless identity."""
    victim = _spawn_agent(tmp_path, server.port, "node-a", "sleep", ttl=1.0)
    observer = RemoteBackend(port=server.port, lease_ttl=10.0)
    try:
        report = _read_report(victim)
        ident_prefix = "cilium/state/identities/v1/"
        slaves = observer.list_prefix(ident_prefix + "value/")
        assert slaves, "agent should hold lease-backed slave keys"
        os.kill(victim.pid, signal.SIGKILL)
        victim.wait(timeout=10)
        deadline = time.time() + 10
        while time.time() < deadline:
            if not observer.list_prefix(ident_prefix + "value/"):
                break
            time.sleep(0.2)
        assert observer.list_prefix(ident_prefix + "value/") == {}, \
            "slave keys must vanish after the dead agent's TTL"
        # masters still exist until GC reclaims them
        masters = observer.list_prefix(ident_prefix + "id/")
        assert masters
        from cilium_tpu.kvstore.allocator import Allocator
        gc_alloc = Allocator(observer, "cilium/state/identities/v1",
                             node="gc-node", min_id=256, max_id=65535)
        reclaimed = gc_alloc.run_gc()
        assert reclaimed == len(masters)
        assert observer.list_prefix(ident_prefix + "id/") == {}
        gc_alloc.close()
    finally:
        observer.close()
        if victim.poll() is None:
            victim.kill()
