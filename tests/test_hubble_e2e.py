"""Hubble end-to-end: live daemon + REST /flows + `cilium hubble
observe`, registry-driven relay federation with a peer killed
mid-query, bugtool/debuginfo flow members, and the L7 feeds (DNS
poller rcodes, HTTP response-status sampling)."""

import io
import json
import sys
import time

import numpy as np
import pytest

from cilium_tpu.cli import Client, main as cli_main
from cilium_tpu.daemon import Daemon
from cilium_tpu.daemon.rest import APIServer
from cilium_tpu.datapath.engine import make_full_batch
from cilium_tpu.utils.option import DaemonConfig

RULES_JSON = """
[{
  "endpointSelector": {"matchLabels": {"id": "server"}},
  "ingress": [
    {"fromEndpoints": [{"matchLabels": {"id": "client"}}]}
  ],
  "labels": ["k8s:policy=web"]
}]
"""


@pytest.fixture
def agent(tmp_path):
    cfg = DaemonConfig(state_dir=str(tmp_path / "state"))
    d = Daemon(config=cfg, builders=2)
    server = APIServer(d).start()
    yield d, server
    server.shutdown()
    d.shutdown()


def _cli(server, *argv):
    out = io.StringIO()
    old = sys.stdout
    sys.stdout = out
    try:
        rc = cli_main(["--api", server.base_url, *argv])
    finally:
        sys.stdout = old
    return rc, out.getvalue()


def _drive_traffic(d, c):
    """Endpoints + policy + one processed batch; returns the
    identities dict and the dropped flow's dport."""
    c.put("/endpoint/100", {"ipv4": "10.0.0.10",
                            "labels": ["k8s:id=server"]})
    c.put("/endpoint/200", {"ipv4": "10.0.0.20",
                            "labels": ["k8s:id=client"]})
    c.request("PUT", "/policy", json.loads(RULES_JSON))
    assert d.wait_for_policy_revision()
    idents = {tuple(i["labels"]): i["id"] for i in c.get("/identity")}
    client_id = idents[("k8s:id=client",)]
    slot = d.endpoints.lookup(100).table_slot
    batch = make_full_batch(
        endpoint=[slot, slot], saddr=["10.0.0.20", "10.99.0.9"],
        daddr=["10.0.0.10"] * 2, sport=[40000, 40001],
        dport=[9999, 22], direction=[0, 0], length=[111, 222])
    verdict, event, identity, _nat = d.datapath.process(batch,
                                                        now=1234)
    v = np.asarray(verdict)
    assert v[0] == 0 and v[1] < 0
    d.monitor.ingest_batch(np.asarray(event),
                           np.asarray(batch.endpoint),
                           np.asarray(identity),
                           np.asarray(batch.dport),
                           np.asarray(batch.proto),
                           np.asarray(batch.length))
    return client_id


def test_flows_rest_and_cli_observe(agent):
    d, server = agent
    c = Client(server.base_url)
    client_id = _drive_traffic(d, c)

    # REST: unfiltered, then filtered by verdict + identity
    out = c.get("/flows?n=50")
    assert out["node"] == d.node_name
    assert len(out["flows"]) == 2
    drops = c.get(f"/flows?verdict=DROPPED&n=50")
    assert len(drops["flows"]) == 1
    assert drops["flows"][0]["dport"] == 22
    assert drops["flows"][0]["drop_reason"]
    allowed = c.get(f"/flows?verdict=FORWARDED&identity={client_id}")
    assert len(allowed["flows"]) == 1
    assert allowed["flows"][0]["src_identity"] == client_id
    # bad predicate -> 400
    with pytest.raises(SystemExit):
        c.get("/flows?verdict=BOGUS")

    # the acceptance-path CLI: filtered observe against the live agent
    rc, text = _cli(server, "hubble", "observe", "--verdict",
                    "DROPPED", "--identity", str(2))
    assert rc == 0
    # identity 2 == WORLD (the unknown 10.99.0.9 source)
    assert "DROPPED" in text and "dport=22" in text
    rc, text = _cli(server, "hubble", "observe", "--verdict",
                    "DROPPED", "--identity", str(client_id))
    assert rc == 0 and "DROPPED" not in text  # client flow was allowed
    rc, text = _cli(server, "hubble", "observe", "--json", "-n", "5")
    assert rc == 0
    lines = [json.loads(l) for l in text.strip().splitlines()]
    assert len(lines) == 2

    # stats: store + on-device aggregation visible
    rc, text = _cli(server, "hubble", "stats", "--aggregated")
    assert rc == 0
    stats = json.loads(text)
    assert stats["store"]["seq"] == 2
    assert stats["aggregation"]["occupied"] >= 2
    agg = {(f["src-identity"], f["dport"]): f for f in stats["flows"]}
    assert (client_id, 9999) in agg
    assert agg[(client_id, 9999)]["bytes"] == 111

    # the device table also rides the map-dump surface
    inv = c.get("/map")
    assert "hubble-flows" in inv
    dump = c.get("/map/hubble-flows")
    assert len(dump) == stats["aggregation"]["occupied"]


def test_flows_since_cursor_pages_forward(agent):
    d, server = agent
    c = Client(server.base_url)
    _drive_traffic(d, c)
    first = c.get("/flows?n=50")
    cursor = first["flows"][0]["seq"]  # oldest flow's cursor
    rest = c.get(f"/flows?since={cursor}&n=50")
    seqs = [f["seq"] for f in rest["flows"]]
    assert seqs == [f["seq"] for f in first["flows"][1:]]
    assert all(s > cursor for s in seqs)


def test_monitor_since_cursor_over_rest(agent):
    d, server = agent
    c = Client(server.base_url)
    _drive_traffic(d, c)
    events = c.get("/monitor?n=100")
    assert all("seq" in e for e in events)
    cursor = events[1]["seq"]
    later = c.get(f"/monitor?since={cursor}&n=100")
    assert [e["seq"] for e in later] == \
        [e["seq"] for e in events if e["seq"] > cursor]


def test_relay_federation_with_peer_killed_mid_query(tmp_path):
    """Two simulated nodes federate /flows through the registry; one
    is killed and the federated answer degrades to a flagged partial,
    then recovers when the peer returns."""
    from cilium_tpu.kvstore.memory import InMemoryBackend, MemStore

    store = MemStore()
    daemons, servers = [], []
    for i, name in enumerate(("node-a", "node-b")):
        cfg = DaemonConfig(state_dir=str(tmp_path / name))
        d = Daemon(config=cfg, kvstore_backend=InMemoryBackend(store),
                   node_name=name)
        server = APIServer(d).start()
        # publish the node WITH its hubble observer address: peers'
        # relays discover it through the shared registry
        d.register_node(f"10.50.0.{i + 1}", f"10.6{i}.0.0/16",
                        hubble_address=server.base_url)
        daemons.append(d)
        servers.append(server)
    try:
        a, b = daemons
        # distinct flows on each node
        for d, dport in ((a, 80), (b, 443)):
            from cilium_tpu.hubble.flow import FlowRecord
            d.hubble.ingest(FlowRecord(
                seq=0, timestamp=time.time(), node=d.node_name,
                verdict="FORWARDED", src_identity=300,
                dst_identity=400, dport=dport, proto=6))

        def wait_for(fn, timeout=5.0):
            deadline = time.time() + timeout
            while time.time() < deadline:
                if fn():
                    return True
                time.sleep(0.05)
            return fn()

        # both relays see both nodes (self + registry peer)
        assert wait_for(lambda: len(a.hubble_relay.peers()) == 2)
        ca = Client(servers[0].base_url)
        out = ca.get("/flows?federated=true&n=50")
        assert not out["partial"]
        assert {f["dport"] for f in out["flows"]} == {80, 443}
        assert {n["name"] for n in out["nodes"]} == \
            {"node-a", "default/node-b"}

        # kill node-b's API server: the next federated query must
        # fail open with node-b flagged, node-a's flows intact
        servers[1].shutdown()
        out = ca.get("/flows?federated=true&n=50")
        assert out["partial"]
        status = {n["name"]: n["status"] for n in out["nodes"]}
        assert status["node-a"] == "ok"
        assert status["default/node-b"] in ("error", "timeout",
                                            "breaker-open")
        assert {f["dport"] for f in out["flows"]} == {80}
        # repeat queries trip the breaker to a bounded probe cadence
        ca.get("/flows?federated=true&n=50")
        out = ca.get("/flows?federated=true&n=50")
        status = {n["name"]: n for n in out["nodes"]}
        health = {h["name"]: h for h in a.hubble_relay.node_health()}
        assert health["default/node-b"]["breaker"] in ("open",
                                                       "half-open")

        # recovery: restart node-b's observer on the SAME port
        servers[1] = APIServer(daemons[1],
                               port=servers[1].port).start()

        def recovered():
            out = ca.get("/flows?federated=true&n=50")
            return not out["partial"] and \
                {f["dport"] for f in out["flows"]} == {80, 443}

        assert wait_for(recovered, timeout=8.0)
        # relay health reflects the closed breaker again
        health = {h["name"]: h for h in a.hubble_relay.node_health()}
        assert health["default/node-b"]["breaker"] == "closed"
        # federated CLI shows the merged stream
        rc, text = _cli(servers[0], "hubble", "observe", "--federated",
                        "--json")
        assert rc == 0
        assert {json.loads(l)["dport"]
                for l in text.strip().splitlines()} == {80, 443}
    finally:
        for s in servers:
            try:
                s.shutdown()
            except Exception:
                pass
        for d in daemons:
            d.shutdown()


def test_bugtool_and_debuginfo_include_flow_state(agent, tmp_path):
    d, server = agent
    c = Client(server.base_url)
    _drive_traffic(d, c)

    # in-process bugtool archive
    import tarfile
    from cilium_tpu.bugtool import collect
    path = collect(d, str(tmp_path / "bt.tar.gz"))
    with tarfile.open(path) as tar:
        names = {n.split("/", 1)[1] for n in tar.getnames()}
        assert "hubble-flows.json" in names
        assert "hubble-aggregation.json" in names
        assert "hubble-relay.json" in names
        member = [n for n in tar.getnames()
                  if n.endswith("hubble-aggregation.json")][0]
        agg = json.load(tar.extractfile(member))
        assert agg["stats"]["occupied"] >= 2
        assert len(agg["flows"]) == agg["stats"]["occupied"]

    # remote (CLI-path) bugtool
    from cilium_tpu.bugtool import collect_remote
    rpath = collect_remote(c, str(tmp_path / "btr.tar.gz"))
    with tarfile.open(rpath) as tar:
        names = {n.split("/", 1)[1] for n in tar.getnames()}
        assert "hubble-flows.json" in names
        assert "hubble-stats.json" in names

    # debuginfo carries the hubble block
    info = c.get("/debuginfo")
    assert info["hubble"] is not None
    assert len(info["hubble"]["flows"]) == 2
    assert info["hubble"]["aggregation"]["occupied"] >= 2
    assert isinstance(info["hubble"]["relay"], list)


def test_dns_poller_feeds_flow_stream(agent):
    d, server = agent
    c = Client(server.base_url)

    def lookup(names):
        return {n: (["1.2.3.4"], 60) if n.startswith("ok")
                else ([], 30) for n in names}

    poller = d.start_fqdn_poller(lookup, interval=3600)
    poller._names.update({"ok.example.com", "missing.example.com"})
    poller.poll_once()
    flows = c.get("/flows?l7_protocol=dns&n=50")["flows"]
    by_name = {f["l7_path"]: f for f in flows}
    assert by_name["ok.example.com"]["l7_status"] == 0
    assert by_name["missing.example.com"]["l7_status"] == 3
    from cilium_tpu.utils.metrics import HUBBLE_DNS_RESPONSES
    assert HUBBLE_DNS_RESPONSES.value(labels={"rcode": "3"}) >= 1


def test_http_status_line_parse():
    from cilium_tpu.l7.http import parse_status_line
    assert parse_status_line(b"HTTP/1.1 200 OK") == 200
    assert parse_status_line(b"HTTP/1.0 403 Forbidden") == 403
    assert parse_status_line(b"HTTP/1.1 abc") is None
    assert parse_status_line(b"GET / HTTP/1.1") is None
    assert parse_status_line(b"HTTP/1.1 9000 nope") is None


def test_status_carries_hubble_block(agent):
    d, server = agent
    c = Client(server.base_url)
    st = c.get("/healthz")
    assert st["hubble"]["node"] == d.node_name
    assert "store" in st["hubble"]
