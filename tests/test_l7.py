"""L7 engine tests: HTTP, Kafka (wire + ACL), DNS/FQDN, parser framework,
proxy manager (mirrors reference pkg/kafka, pkg/fqdn, proxylib tests)."""

import struct

import numpy as np
import pytest

from cilium_tpu.l7.dns import DNSCache, DNSPolicyEngine, DNSPoller
from cilium_tpu.l7.http import HTTPPolicyEngine, HTTPRequest
from cilium_tpu.l7.kafka import (KafkaPolicyEngine, KafkaRequest,
                                 parse_kafka_request)
from cilium_tpu.l7.parser import (Connection, Instance, LineParser, Op,
                                  REGISTRY)
from cilium_tpu.labels import LabelArray
from cilium_tpu.policy.api import (FQDNSelector, PortRuleHTTP, PortRuleKafka,
                                   PortRuleL7, Rule, EgressRule,
                                   EndpointSelector)
from cilium_tpu.policy.l4 import L4Filter, L7DataMap, PARSER_TYPE_HTTP
from cilium_tpu.policy.api import L7Rules, WILDCARD_SELECTOR
from cilium_tpu.proxy import ProxyManager, proxy_id


# --- HTTP -------------------------------------------------------------------

def test_http_engine_method_path():
    eng = HTTPPolicyEngine([
        PortRuleHTTP(method="GET", path="/public/.*"),
        PortRuleHTTP(method="POST", path="/upload"),
    ])
    reqs = [HTTPRequest("GET", "/public/a.html"),
            HTTPRequest("GET", "/private/a"),
            HTTPRequest("POST", "/upload"),
            HTTPRequest("PUT", "/upload")]
    v = eng.check(reqs)
    np.testing.assert_array_equal(v, [True, False, True, False])


def test_http_engine_host_and_headers():
    eng = HTTPPolicyEngine([
        PortRuleHTTP(method="GET", host=".*\\.example\\.com",
                     headers=("X-Token secret",)),
    ])
    ok = eng.check_one(HTTPRequest("GET", "/x", host="api.example.com",
                                   headers={"X-Token": "secret"}))
    assert ok
    assert not eng.check_one(HTTPRequest("GET", "/x", host="api.example.com",
                                         headers={"X-Token": "wrong"}))
    assert not eng.check_one(HTTPRequest("GET", "/x", host="evil.com",
                                         headers={"X-Token": "secret"}))
    assert not eng.check_one(HTTPRequest("GET", "/x",
                                         host="api.example.com"))


def test_http_empty_rules_allow_all():
    eng = HTTPPolicyEngine([])
    assert eng.check_one(HTTPRequest("DELETE", "/anything"))


def test_http_empty_rule_matches_everything():
    eng = HTTPPolicyEngine([PortRuleHTTP()])
    assert eng.check_one(HTTPRequest("PATCH", "/whatever", host="x"))


# --- Kafka ------------------------------------------------------------------

def _kafka_frame(api_key, version, client_id, body=b""):
    hdr = struct.pack(">hhi", api_key, version, 1)
    cid = struct.pack(">h", len(client_id)) + client_id.encode()
    payload = hdr + cid + body
    return struct.pack(">i", len(payload)) + payload


def _metadata_req(topics, client_id="cli"):
    body = struct.pack(">i", len(topics))
    for t in topics:
        body += struct.pack(">h", len(t)) + t.encode()
    return _kafka_frame(3, 0, client_id, body)


def _produce_req(topic, client_id="cli"):
    body = struct.pack(">hi", 1, 1000)  # acks, timeout
    body += struct.pack(">i", 1)
    body += struct.pack(">h", len(topic)) + topic.encode()
    return _kafka_frame(0, 0, client_id, body)


def test_kafka_parse():
    req = parse_kafka_request(_metadata_req(["logs", "events"]))
    assert req.api_key == 3
    assert req.client_id == "cli"
    assert req.topics == ["logs", "events"]
    req = parse_kafka_request(_produce_req("logs"))
    assert req.api_key == 0 and req.topics == ["logs"]


def test_kafka_acl_topic():
    eng = KafkaPolicyEngine([PortRuleKafka(api_key="produce", topic="logs")])
    assert eng.allows(parse_kafka_request(_produce_req("logs")))
    assert not eng.allows(parse_kafka_request(_produce_req("secret")))
    # fetch not allowed by produce-key rule
    eng2 = KafkaPolicyEngine([PortRuleKafka(role="produce", topic="logs")])
    # produce role includes metadata + apiversions
    assert eng2.allows(parse_kafka_request(_metadata_req(["logs"])))
    assert not eng2.allows(parse_kafka_request(_metadata_req(["other"])))


def test_kafka_all_topics_must_be_allowed():
    """MatchesRule: every topic in the request needs a covering rule."""
    eng = KafkaPolicyEngine([
        PortRuleKafka(topic="a"), PortRuleKafka(topic="b")])
    assert eng.allows(parse_kafka_request(_metadata_req(["a"])))
    assert eng.allows(parse_kafka_request(_metadata_req(["a", "b"])))
    assert not eng.allows(parse_kafka_request(_metadata_req(["a", "c"])))


def test_kafka_client_id_and_version():
    eng = KafkaPolicyEngine([PortRuleKafka(client_id="good")])
    assert eng.allows(parse_kafka_request(_metadata_req([], "good")))
    assert not eng.allows(parse_kafka_request(_metadata_req([], "evil")))
    eng = KafkaPolicyEngine([PortRuleKafka(api_version="0")])
    assert eng.allows(parse_kafka_request(_metadata_req([])))
    eng = KafkaPolicyEngine([PortRuleKafka(api_version="5")])
    assert not eng.allows(parse_kafka_request(_metadata_req([])))


def test_kafka_empty_rules_allow():
    assert KafkaPolicyEngine([]).allows(
        parse_kafka_request(_metadata_req(["x"])))


# --- DNS / FQDN -------------------------------------------------------------

def test_dns_cache_ttl():
    c = DNSCache()
    c.update("cilium.io", ["1.2.3.4"], ttl=60, now=100)
    assert c.lookup("cilium.io", now=120) == ["1.2.3.4"]
    assert c.lookup("CILIUM.IO.", now=120) == ["1.2.3.4"]  # canonical
    assert c.lookup("cilium.io", now=161) == []
    assert c.gc(now=161) == 1


def test_dns_policy_engine():
    eng = DNSPolicyEngine([FQDNSelector(match_name="cilium.io"),
                           FQDNSelector(match_pattern="*.corp.net")])
    allowed = eng.allowed(["cilium.io", "a.corp.net", "evil.com",
                           "x.y.corp.net"])
    np.testing.assert_array_equal(allowed, [True, True, False, False])


def test_dns_poller_and_injection():
    cache = DNSCache()
    rule = Rule(endpoint_selector=EndpointSelector.parse("app"),
                egress=[EgressRule(
                    to_fqdns=[FQDNSelector(match_name="svc.example.com")])])
    changes = []
    poller = DNSPoller(
        cache,
        lookup=lambda names: {n: (["10.5.5.5"], 300) for n in names},
        on_change=lambda names: changes.append(names))
    poller.register_rule(rule)
    changed = poller.poll_once(now=100)
    assert changed == {"svc.example.com"}
    assert changes == [{"svc.example.com"}]

    from cilium_tpu.l7.dns import inject_to_cidr_set
    assert inject_to_cidr_set(rule, cache, now=100)
    assert rule.egress[0].to_cidr_set[0].cidr == "10.5.5.5/32"
    assert rule.egress[0].to_cidr_set[0].generated

    # second poll with same results: no change
    assert poller.poll_once(now=101) == set()


# --- parser framework -------------------------------------------------------

def test_line_parser_policy():
    inst = Instance()
    assert inst.on_new_connection(
        "line", 1, ingress=True, src_id=100, dst_id=200,
        l7_rules=[PortRuleL7.from_dict({"cmd": "GET"})])
    ops = inst.on_data(1, reply=False, end_stream=False,
                       data=b"GET x\nPUT y\nGET z\n")
    assert [(o.op, o.n) for o in ops] == [
        (Op.PASS, 6), (Op.DROP, 6), (Op.PASS, 6)]
    inst.close(1)
    assert len(inst) == 0


def test_line_parser_partial_frames():
    inst = Instance()
    inst.on_new_connection("line", 2, ingress=False, src_id=1, dst_id=2)
    ops = inst.on_data(2, reply=False, end_stream=False, data=b"GET par")
    assert ops[-1].op == Op.MORE
    # proxy re-presents the whole buffer once more data arrives
    ops = inst.on_data(2, reply=False, end_stream=False,
                       data=b"GET partial\n")
    assert (ops[0].op, ops[0].n) == (Op.PASS, 12)


def test_block_parser():
    inst = Instance()
    inst.on_new_connection("block", 3, ingress=True, src_id=1, dst_id=2)
    data = b"0005Hello0003Dxx"
    ops = inst.on_data(3, reply=False, end_stream=False, data=data)
    assert [(o.op, o.n) for o in ops] == [(Op.PASS, 9), (Op.DROP, 7)]


def test_unknown_protocol_rejected():
    inst = Instance()
    assert not inst.on_new_connection("nosuch", 9, ingress=True,
                                      src_id=1, dst_id=2)


# --- proxy manager ----------------------------------------------------------

def _http_filter(port=80):
    l7map = L7DataMap()
    l7map[WILDCARD_SELECTOR] = L7Rules(http=[PortRuleHTTP(method="GET")])
    return L4Filter(port=port, protocol="TCP", u8proto=6,
                    l7_parser=PARSER_TYPE_HTTP, l7_rules_per_ep=l7map,
                    ingress=True)


def test_proxy_redirect_lifecycle():
    pm = ProxyManager()
    flt = _http_filter()
    r = pm.create_or_update_redirect(flt, endpoint_id=42)
    assert 10000 <= r.proxy_port <= 20000
    assert r.id == proxy_id(42, True, "TCP", 80)
    # same key: same port
    r2 = pm.create_or_update_redirect(flt, endpoint_id=42)
    assert r2.proxy_port == r.proxy_port
    assert len(pm) == 1
    # different endpoint: new port
    r3 = pm.create_or_update_redirect(flt, endpoint_id=43)
    assert r3.proxy_port != r.proxy_port
    assert pm.remove_redirect(r.id)
    assert not pm.remove_redirect(r.id)


def test_proxy_http_check_and_access_log():
    pm = ProxyManager()
    r = pm.create_or_update_redirect(_http_filter(), endpoint_id=1)
    v = pm.check_http(r, LabelArray.parse_select("whoever"),
                      [HTTPRequest("GET", "/a"), HTTPRequest("POST", "/a")])
    np.testing.assert_array_equal(v, [True, False])
    tail = pm.access_log.tail()
    assert len(tail) == 2
    assert tail[0].verdict == "forwarded"
    assert tail[1].verdict == "denied"
