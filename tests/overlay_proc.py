"""Subprocess agent for the cross-node overlay/tunnel e2e test.

Two of these processes share a TCP kvstore.  Each runs a full Daemon,
registers its node (pod CIDR + node IP) in the node registry, and
creates one endpoint.  Node discovery programs each side's device
tunnel LPM via the NodeManager.

Role "sender": waits until the peer node appears, then processes an
egress packet from its endpoint to the peer's pod IP and prints the
encap decision — the tunnel endpoint (must be the peer's node IP) and
the tunnel identity (must be the sending endpoint's security identity).

Role "receiver": prints readiness, then reads one JSON "wire packet"
per line from stdin — {saddr, daddr, dport, tunnel_id} — and processes
it as from-overlay ingress traffic into its endpoint, printing the
verdict.  Its policy allows only the sender's label set, and its
ipcache deliberately has NO entry for the sender's pod IP in one of the
scenarios, so an allow verdict proves the identity was taken from the
tunnel key (bpf_overlay.c:151), not from an ipcache lookup.

Usage: python tests/overlay_proc.py <kv_port> <node> <role>
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from cilium_tpu.daemon import Daemon  # noqa: E402
from cilium_tpu.datapath.engine import make_full_batch  # noqa: E402
from cilium_tpu.datapath.events import TRACE_TO_OVERLAY  # noqa: E402
from cilium_tpu.kvstore.remote import RemoteBackend  # noqa: E402
from cilium_tpu.node import Node, NodeAddress  # noqa: E402
from cilium_tpu.policy.jsonio import rules_from_json  # noqa: E402
from cilium_tpu.utils.option import DaemonConfig  # noqa: E402


def u32_to_ipv4(v: int) -> str:
    v = int(v) & 0xFFFFFFFF
    return ".".join(str((v >> s) & 0xFF) for s in (24, 16, 8, 0))

SENDER_CIDR, RECEIVER_CIDR = "10.60.1.0/24", "10.60.2.0/24"
SENDER_NODE_IP, RECEIVER_NODE_IP = "192.168.7.1", "192.168.7.2"
SENDER_POD, RECEIVER_POD = "10.60.1.9", "10.60.2.9"


def wait_for(pred, timeout=15.0):
    import time
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.1)
    return False


def main() -> None:
    kv_port = int(sys.argv[1])
    node_name = sys.argv[2]
    role = sys.argv[3]
    is_sender = role == "sender"

    kv = RemoteBackend(port=kv_port, lease_ttl=10.0)
    d = Daemon(config=DaemonConfig(), kvstore_backend=kv,
               node_name=node_name)
    me = Node(name=node_name,
              addresses=[NodeAddress("InternalIP",
                                     SENDER_NODE_IP if is_sender
                                     else RECEIVER_NODE_IP)],
              ipv4_alloc_cidr=SENDER_CIDR if is_sender else RECEIVER_CIDR)
    d.node_registry.register_local(me)

    try:
        if is_sender:
            run_sender(d)
        else:
            run_receiver(d)
    finally:
        d.shutdown()
        kv.close()


def run_sender(d: Daemon) -> None:
    ep = d.endpoint_create(1, ipv4=SENDER_POD,
                           labels=["k8s:app=overlay-client"])
    # an explicit allow-all egress rule keeps the verdict deterministic
    rev = d.policy_add(rules_from_json(json.dumps([
        {"endpointSelector": {"matchLabels": {"app": "overlay-client"}},
         "egress": [{"toEntities": ["all"]}]}])))
    d.wait_for_policy_revision(rev)
    assert wait_for(lambda: d.datapath.tunnel_prefixes.get(RECEIVER_CIDR)
                    is not None), "peer node never appeared"

    batch = make_full_batch(endpoint=[ep.table_slot],
                            saddr=[SENDER_POD], daddr=[RECEIVER_POD],
                            sport=[40001], dport=[8080], direction=[1])
    verdict, event, identity, nat = d.datapath.process(batch, now=1000)
    out = {
        "verdict": int(np.asarray(verdict)[0]),
        "event": int(np.asarray(event)[0]),
        "to_overlay": int(np.asarray(event)[0]) == TRACE_TO_OVERLAY,
        "tunnel_ep": u32_to_ipv4(
            np.asarray(nat.tunnel_ep).astype(np.uint32)[0]),
        "tunnel_id": int(np.asarray(nat.tunnel_id)[0]),
        "endpoint_identity": ep.security_identity,
        "saddr": SENDER_POD, "daddr": RECEIVER_POD, "dport": 8080,
    }
    print(json.dumps(out), flush=True)


def run_receiver(d: Daemon) -> None:
    ep = d.endpoint_create(2, ipv4=RECEIVER_POD,
                           labels=["k8s:app=overlay-server"])
    # L3 ingress policy: only peers with the overlay-client label may
    # reach overlay-server.  The sender's identity for that label set
    # is shared cluster-wide via the distributed allocator.
    rev = d.policy_add(rules_from_json(json.dumps([{
        "endpointSelector": {"matchLabels": {"app": "overlay-server"}},
        "ingress": [{"fromEndpoints": [
            {"matchLabels": {"app": "overlay-client"}}]}],
    }])))
    d.wait_for_policy_revision(rev)
    print(json.dumps({"ready": True,
                      "endpoint_identity": ep.security_identity}),
          flush=True)
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        wire = json.loads(line)
        if wire.get("op") == "quit":
            return
        # a freshly allocated remote identity triggers an async policy
        # recompute (identity-change regen); wait for it to land before
        # classifying, like the reference's revision wait after
        # TriggerPolicyUpdates.  Reserved identities (< 256) are static.
        if wire["tunnel_id"] >= 256:
            wait_for(lambda: d.identity_allocator.lookup_by_id(
                wire["tunnel_id"]) is not None)
            # force the recompute synchronously so the verdict below is
            # deterministic (the async identity-change trigger races)
            d.endpoints.regenerate_all("wire-packet")
            d.endpoints.wait_for_quiesce()
        batch = make_full_batch(
            endpoint=[ep.table_slot],
            saddr=[wire["saddr"]], daddr=[wire["daddr"]],
            sport=[wire.get("sport", 40001)], dport=[wire["dport"]],
            direction=[0],
            from_overlay=[1], tunnel_id=[wire["tunnel_id"]])
        verdict, event, identity, _nat = d.datapath.process(batch,
                                                            now=2000)
        print(json.dumps({
            "verdict": int(np.asarray(verdict)[0]),
            "identity_used": int(np.asarray(identity)[0]),
            "ipcache_has_sender": d.ipcache.lookup_by_ip(wire["saddr"])
            is not None,
        }), flush=True)


if __name__ == "__main__":
    main()
