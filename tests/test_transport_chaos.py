"""Transport resilience under injected faults (round-5 ADVICE #1-#5 +
VERDICT weak #5).

Every control-plane transport is driven through the failure modes the
resilience layer (utils/resilience.py) exists to absorb, with faults
injected by utils/faultinject.py:

- etcd watch compaction with deletes in the blind window: allocator,
  ipcache, and node-registry consumers must converge with ZERO stale
  entries via the relist-and-diff synthetic-event path;
- a connection reset between send and reply on a create_only lock
  txn: verify-on-retry reclaims the applied-but-unacknowledged lock
  instead of orphaning it until lease expiry;
- a flapping apiserver: the reflector's circuit breaker degrades to a
  bounded probe cadence, then recovers when the peer heals;
- a stalled peer on the verdict-service handshake (and mid-frame):
  dropped within the deadline, accept loop keeps serving.
"""

import http.client
import json
import socket
import struct
import sys
import threading
import time

import pytest

from cilium_tpu.ipcache.ipcache import IPCache
from cilium_tpu.ipcache.kvstore_sync import (IP_IDENTITIES_PATH,
                                             IPIdentityWatcher)
from cilium_tpu.kvstore.allocator import Allocator
from cilium_tpu.kvstore.etcd import EtcdBackend
from cilium_tpu.kvstore.mini_etcd import MiniEtcd
from cilium_tpu.kvstore.remote import RemoteBackend, RemoteTimeout
from cilium_tpu.kvstore.server import KVStoreServer
from cilium_tpu.node.registry import NODES_PATH, NodeRegistry
from cilium_tpu.utils import resilience
from cilium_tpu.utils.faultinject import FaultProxy, FaultySocket
from cilium_tpu.utils.resilience import CircuitBreaker, Deadline

ALLOC_PREFIX = "cilium/test-chaos-alloc"


def _wait_for(cond, timeout=10.0, interval=0.05, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


@pytest.fixture()
def etcd_server():
    srv = MiniEtcd(reap_interval=0.1).start()
    yield srv
    srv.shutdown()


@pytest.fixture()
def proxy(etcd_server):
    p = FaultProxy("127.0.0.1", etcd_server.port).start()
    yield p
    p.close()


def _ip_key(ip):
    return f"{IP_IDENTITIES_PATH}/{ip}"


def _ip_val(ip, ident):
    return json.dumps({"IP": ip, "ID": ident, "HostIP": None,
                       "Metadata": ""}).encode()


def _node_val(name):
    return json.dumps({"Name": name, "Cluster": "default",
                       "ClusterID": 0, "IPAddresses": [],
                       "IPv4AllocCIDR": None,
                       "IPv6AllocCIDR": None}).encode()


# ---------------------------------------------------- compaction window

def test_compaction_blind_window_leaves_no_stale_entries(etcd_server,
                                                         proxy):
    """The VERDICT weak #5 scenario end-to-end: watch streams die, the
    world changes, the history is compacted away, and the reconnecting
    watcher must relist-and-diff — allocator, ipcache, and node
    consumers all converge with the blind-window deletes applied."""
    writer = EtcdBackend(port=etcd_server.port, lease_ttl=30.0)
    victim = EtcdBackend(host="127.0.0.1", port=proxy.port,
                         lease_ttl=30.0)
    relists_before = resilience.WATCH_RELISTS.value(
        labels={"transport": "etcd"})
    try:
        # seed the world through the direct writer
        writer.set(_ip_key("10.1.0.1"), _ip_val("10.1.0.1", 1001))
        writer.set(_ip_key("10.1.0.2"), _ip_val("10.1.0.2", 1002))
        writer.set(f"{NODES_PATH}/default/n1", _node_val("n1"))
        writer.set(f"{NODES_PATH}/default/n2", _node_val("n2"))
        writer.set(f"{ALLOC_PREFIX}/id/100", b"keyA")
        writer.set(f"{ALLOC_PREFIX}/id/101", b"keyB")

        # three real consumers on the proxied victim backend
        cache = IPCache()
        ipwatch = IPIdentityWatcher(victim, cache)
        ipwatch.start()
        registry = NodeRegistry(victim)
        alloc = Allocator(victim, ALLOC_PREFIX, node="victim",
                          min_id=100, max_id=200)
        assert ipwatch.wait_synced(10)
        assert registry.wait_synced(10)
        _wait_for(lambda: cache.lookup_by_ip("10.1.0.2/32") == 1002,
                  msg="ipcache seed")
        _wait_for(lambda: registry.get("default/n2") is not None,
                  msg="node seed")
        _wait_for(lambda: alloc.get_by_id(101) == "keyB",
                  msg="allocator seed")

        # blind window: kill every stream, mutate, compact the history
        proxy.pause()
        proxy.reset_all()
        writer.delete(_ip_key("10.1.0.2"))
        writer.delete(f"{NODES_PATH}/default/n2")
        writer.delete(f"{ALLOC_PREFIX}/id/101")
        writer.set(_ip_key("10.1.0.3"), _ip_val("10.1.0.3", 1003))
        etcd_server.compact()
        proxy.resume()

        # relist-and-diff must deliver the synthetic DELETEs (stale
        # entries removed) and the blind-window CREATE
        _wait_for(lambda: cache.lookup_by_ip("10.1.0.2/32") is None,
                  msg="stale ipcache entry removed")
        _wait_for(lambda: registry.get("default/n2") is None,
                  msg="stale node removed")
        _wait_for(lambda: alloc.get_by_id(101) is None,
                  msg="stale allocator id removed")
        _wait_for(lambda: cache.lookup_by_ip("10.1.0.3/32") == 1003,
                  msg="blind-window create delivered")
        # survivors intact
        assert cache.lookup_by_ip("10.1.0.1/32") == 1001
        assert registry.get("default/n1") is not None
        assert alloc.get_by_id(100) == "keyA"
        # and the recovery is visible in the exported counters
        assert resilience.WATCH_RELISTS.value(
            labels={"transport": "etcd"}) > relists_before
        assert resilience.status_summary()["watch-relists"] >= 1

        ipwatch.stop()
        registry.close()
    finally:
        victim.close()
        writer.close()


# ------------------------------------------------- ambiguous mutations

def test_lock_txn_reset_between_send_and_reply_not_orphaned(
        etcd_server, proxy):
    """ADVICE #5: the create_only lock txn is applied but its reply is
    swallowed and the connection reset.  verify-on-retry reads the
    key back — value == own token — and reclaims the lock instead of
    leaving it orphaned until the lease TTL."""
    client = EtcdBackend(host="127.0.0.1", port=proxy.port,
                         lease_ttl=10.0)
    observer = EtcdBackend(port=etcd_server.port, lease_ttl=30.0)
    verifies_before = resilience.TRANSPORT_VERIFIES.total()
    try:
        proxy.drop_response_once(b"/v3/kv/txn")
        lock = client.lock_path("chaos/resource", timeout=10.0)
        assert proxy.resets_injected == 1, \
            "the txn reply should have been dropped"
        # the store holds exactly OUR token: the first (reply-less)
        # create landed and was reclaimed, not re-created or orphaned
        assert observer.get("chaos/resource.lock") == \
            lock.token.encode()
        assert resilience.TRANSPORT_VERIFIES.total() > verifies_before
        lock.unlock()
        assert observer.get("chaos/resource.lock") is None
        # the path is immediately lockable again
        lock2 = client.lock_path("chaos/resource", timeout=5.0)
        lock2.unlock()
    finally:
        client.close()
        observer.close()


def test_remote_create_only_verify_on_lost_reply():
    """The same ambiguity on the TCP frame transport: a create_only
    whose reply frame is lost resolves by reading the key back, and an
    idempotent read retries blindly within its deadline."""
    srv = KVStoreServer(port=0, expire_interval=0.1).start()
    client = RemoteBackend(port=srv.port, lease_ttl=10.0)
    try:
        orig = client._call_once
        dropped = []

        def lossy(op, timeout, args):
            resp = orig(op, timeout, args)
            if op in ("create_only", "get") and len(dropped) < 2:
                dropped.append(op)
                raise RemoteTimeout(f"{op}: injected reply loss")
            return resp

        client._call_once = lossy
        # mutation: applied server-side, reply "lost" -> verified back
        assert client.create_only("amb-key", b"tok-1") is True
        assert dropped.count("create_only") == 1
        client._call_once = orig
        assert client.get("amb-key") == b"tok-1"
        # a competing create still correctly loses
        assert client.create_only("amb-key", b"tok-2") is False
    finally:
        client.close()
        srv.shutdown()


# ------------------------------------------------------ k8s flapping

class _Sink:
    """Minimal K8sWatcher stand-in for a single reflector."""

    def __init__(self):
        self.events = []
        self._mu = threading.Lock()

    def enqueue_event(self, kind, action, obj):
        with self._mu:
            self.events.append((kind, action, obj))


def test_flapping_apiserver_breaker_bounds_reconnects():
    from cilium_tpu.k8s.client import K8sClient, Reflector
    from cilium_tpu.k8s.fake_apiserver import FakeAPIServer
    fake = FakeAPIServer().start()
    fproxy = FaultProxy("127.0.0.1", fake.port).start()
    fproxy.refuse_connections = True
    sink = _Sink()
    reflector = Reflector(
        K8sClient(f"http://127.0.0.1:{fproxy.port}", timeout=2.0),
        "/api/v1/nodes", "node", sink,
        backoff_base=0.01, backoff_max=0.1,
        breaker=CircuitBreaker("chaos-k8s", failure_threshold=3,
                               reset_timeout=0.1, max_reset=0.5))
    try:
        reflector.start()
        _wait_for(lambda: reflector.breaker.state == "open",
                  timeout=5.0, msg="breaker to open")
        # open: probes only — a bounded trickle, not a hot loop
        before = fproxy.connections_total
        time.sleep(0.6)
        probes = fproxy.connections_total - before
        assert probes <= 5, \
            f"open breaker admitted {probes} connections in 600ms"
        # heal the apiserver: the next admitted probe closes the
        # breaker and the reflector syncs
        fake.upsert("nodes", {"metadata": {"name": "n1"}})
        fproxy.refuse_connections = False
        _wait_for(lambda: reflector.synced.is_set(), timeout=10.0,
                  msg="reflector to sync after heal")
        _wait_for(lambda: reflector.breaker.state == "closed",
                  timeout=10.0, msg="breaker to close")
        _wait_for(lambda: any(a == "added" for _k, a, _o in
                              sink.events), msg="object delivered")
    finally:
        reflector.stop()
        fproxy.close()
        fake.shutdown()


def test_fake_apiserver_idle_watch_heartbeats():
    """ADVICE #4: an idle watch stream still gets periodic writes
    (BOOKMARK chunks), so an abandoned client surfaces as a send
    error instead of a handler thread parked forever."""
    from cilium_tpu.k8s.fake_apiserver import FakeAPIServer
    fake = FakeAPIServer().start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", fake.port,
                                          timeout=3.0)
        conn.request("GET", "/api/v1/pods?watch=true&resourceVersion=0")
        resp = conn.getresponse()
        assert resp.status == 200
        line = resp.readline()
        event = json.loads(line)
        assert event["type"] == "BOOKMARK"
        conn.close()
    finally:
        fake.shutdown()


# ------------------------------------------------- mini-etcd semantics

def test_minietcd_start_revision_zero_means_from_current(etcd_server):
    """ADVICE #1: start_revision=0 must mean 'from current' (real etcd
    semantics), not 'replay all retained history' — otherwise a
    restarted watch re-applies stale DELETEs."""
    backend = EtcdBackend(port=etcd_server.port, lease_ttl=10.0)
    try:
        backend.set("zr/a", b"1")
        backend.delete("zr/a")
        backend.set("zr/b", b"2")
        conn = http.client.HTTPConnection("127.0.0.1",
                                          etcd_server.port,
                                          timeout=2.0)
        payload = json.dumps({"create_request": {
            "key": "enIv",  # base64("zr/")
            "range_end": "enIw",  # base64("zr0")
            "start_revision": "0"}}).encode()
        conn.request("POST", "/v3/watch", body=payload,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        first = json.loads(resp.readline())
        assert first["result"].get("created") is True
        # nothing replayed: the next frame must be the LIVE write
        # below (or an idle progress notify), never history
        backend.set("zr/c", b"3")
        deadline = time.monotonic() + 3.0
        seen = []
        while time.monotonic() < deadline:
            msg = json.loads(resp.readline())
            events = msg.get("result", {}).get("events", [])
            if events:
                seen = events
                break
        assert len(seen) == 1
        assert seen[0]["kv"]["key"] == "enIvYw=="  # base64("zr/c")
        conn.close()
    finally:
        backend.close()


# --------------------------------------------------- verdict deadlines

def _dummy_datapath():
    class _DP:
        def process(self, batch):
            raise AssertionError("no frames should be classified")
    return _DP()


def test_verdict_handshake_stall_dropped_and_service_survives():
    """Acceptance (c): a peer that connects and goes silent during the
    auth handshake is dropped within the deadline; the accept loop
    keeps serving authenticated clients."""
    from cilium_tpu.verdict_service import VerdictClient, VerdictService
    svc = VerdictService(_dummy_datapath(), secret=b"hunter2",
                         handshake_timeout=0.4).start()
    try:
        stalled = socket.create_connection(("127.0.0.1", svc.port),
                                           timeout=5.0)
        challenge = stalled.recv(20)  # MAGIC_AUTH + nonce
        assert len(challenge) == 20
        # ... and say nothing: the server must hang up, not hang
        t0 = time.monotonic()
        rest = stalled.recv(1)
        assert rest == b"", "server should close the stalled peer"
        assert time.monotonic() - t0 < 3.0
        stalled.close()
        # the service still serves: a real handshake completes
        good = VerdictClient("127.0.0.1", svc.port, timeout=5.0,
                             secret=b"hunter2")
        good.close()
    finally:
        svc.shutdown()


def test_verdict_half_frame_stall_dropped():
    """A peer that sends a frame header then stalls mid-payload is
    dropped at the frame deadline (idle BETWEEN frames stays legal)."""
    from cilium_tpu.verdict_service import MAGIC_REQ, VerdictService
    svc = VerdictService(_dummy_datapath(),
                         frame_timeout=0.4).start()
    try:
        sock = socket.create_connection(("127.0.0.1", svc.port),
                                        timeout=5.0)
        # header commits to 4 records (96 payload bytes); send 10
        sock.sendall(struct.pack(">III", MAGIC_REQ, 7, 4))
        sock.sendall(b"\x00" * 10)
        t0 = time.monotonic()
        assert sock.recv(1) == b"", \
            "server should drop the half-frame staller"
        assert time.monotonic() - t0 < 3.0
        sock.close()
        # accept loop unharmed
        probe = socket.create_connection(("127.0.0.1", svc.port),
                                         timeout=5.0)
        probe.close()
    finally:
        svc.shutdown()


# ------------------------------------------------- serializer give-up

def test_serializer_stop_rolls_back_dequeued_unexecuted_item():
    """ADVICE #3: an item already dequeued (but not yet executed) when
    stop() lands must still get the wait(sys.maxsize) give-up call so
    enqueue-time bookkeeping is rolled back."""
    from cilium_tpu.utils.serializer import FunctionQueue
    fq = FunctionQueue("chaos")
    orig_get = fq._q.get
    hook_entered = threading.Event()
    dequeued = threading.Event()
    gate = threading.Event()

    def hooked_get(*a, **kw):
        hook_entered.set()
        item = orig_get(*a, **kw)  # raises Empty on idle polls
        dequeued.set()
        gate.wait(5.0)  # hold the worker between dequeue and execute
        return item

    fq._q.get = hooked_get
    # the worker may still be inside a pre-patch get(timeout=...) that
    # would grab the item un-hooked; only enqueue once the hook is the
    # one polling
    assert hook_entered.wait(5.0)
    ran = []
    giveups = []
    fq.enqueue(lambda: ran.append(True),
               wait_func=lambda n: giveups.append(n) or False)
    assert dequeued.wait(5.0)
    threading.Timer(0.1, gate.set).start()
    fq.stop(drain=False)
    assert ran == [], "the function must not run after stop"
    assert giveups == [sys.maxsize], \
        "the dequeued-but-unexecuted item must get the give-up call"


# ------------------------------------------------------- unit tier

def test_circuit_breaker_lifecycle():
    b = CircuitBreaker("unit", failure_threshold=2, reset_timeout=0.1,
                       max_reset=0.4)
    assert b.allow() and b.state == "closed"
    b.record_failure()
    assert b.state == "closed"
    b.record_failure()
    assert b.state == "open"
    assert not b.allow()
    time.sleep(0.12)
    assert b.allow()  # the single half-open probe
    assert b.state == "half-open"
    assert not b.allow()  # nobody else rides along
    b.record_failure()  # probe failed: re-open, timeout doubled
    assert b.state == "open"
    assert 0.1 < b.retry_in() <= 0.2
    time.sleep(0.25)
    assert b.allow()
    b.record_success()
    assert b.state == "closed" and b.allow()


def test_deadline_and_faulty_socket():
    d = Deadline(0.05)
    assert not d.expired and d.remaining() > 0
    time.sleep(0.06)
    assert d.expired and d.remaining() == 0.0
    assert Deadline(None).remaining() == float("inf")

    a, b = socket.socketpair()
    try:
        fs = FaultySocket(a, partial_write=3)
        fs.sendall(b"0123456789")  # fragmented on the wire...
        got = b""
        while len(got) < 10:
            got += b.recv(10)
        assert got == b"0123456789"  # ...but delivered in full
        fs2 = FaultySocket(a, reset_after_bytes=4)
        with pytest.raises(ConnectionResetError):
            fs2.sendall(b"xxxxxxxx")
    finally:
        a.close()
        b.close()


def test_daemon_status_exports_transport_resilience():
    from cilium_tpu.daemon import Daemon
    from cilium_tpu.daemon.daemon import DaemonConfig
    d = Daemon(config=DaemonConfig())
    try:
        transports = d.status()["transports"]
        for key in ("retries", "deadline-expired", "verify-on-retry",
                    "watch-relists", "synthetic-events",
                    "breaker-transitions", "breakers"):
            assert key in transports
        text = d.metrics_text()
        assert "transport_retries_total" in text
        assert "transport_watch_relists_total" in text
        assert "transport_breaker_transitions_total" in text
    finally:
        d.shutdown()
