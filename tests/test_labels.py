"""Label model tests (mirrors reference pkg/labels/labels_test.go)."""

from cilium_tpu import labels as lbl
from cilium_tpu.labels import (Label, LabelArray, Labels, get_cidr_labels,
                               ip_to_cidr_label, parse_label,
                               parse_select_label)


def test_parse_label_basic():
    l = parse_label("k8s:io.kubernetes.pod.namespace=default")
    assert l.source == "k8s"
    assert l.key == "io.kubernetes.pod.namespace"
    assert l.value == "default"


def test_parse_label_no_source():
    l = parse_label("foo=bar")
    assert l.source == lbl.SOURCE_UNSPEC
    assert l.key == "foo"
    assert l.value == "bar"


def test_parse_label_no_value():
    l = parse_label("container:id.service1")
    assert l.source == "container"
    assert l.key == "id.service1"
    assert l.value == ""


def test_parse_label_reserved_shorthand():
    l = parse_label("$host")
    assert l.source == lbl.SOURCE_RESERVED
    assert l.key == "host"


def test_parse_label_equals_before_colon():
    # '=' before ':' means the whole string before '=' is the key.
    l = parse_label("key=value:with-colon")
    assert l.source == lbl.SOURCE_UNSPEC
    assert l.key == "key"
    assert l.value == "value:with-colon"


def test_parse_select_label_promotes_any():
    l = parse_select_label("foo")
    assert l.source == lbl.SOURCE_ANY
    l2 = parse_select_label("k8s:foo")
    assert l2.source == "k8s"


def test_extended_key():
    assert parse_label("k8s:foo=bar").extended_key == "k8s.foo"
    assert parse_label("foo").extended_key == "any.foo"
    assert parse_select_label("foo").extended_key == "any.foo"


def test_label_array_has_any_wildcard():
    arr = LabelArray.parse("k8s:foo=bar", "container:svc=a")
    assert arr.has("any.foo")
    assert arr.has("k8s.foo")
    assert not arr.has("container.foo")
    assert arr.get("any.svc") == "a"


def test_labels_sorted_list_deterministic():
    a = Labels.from_model(["k8s:a=1", "container:b=2", "z=3"])
    b = Labels.from_model(["z=3", "k8s:a=1", "container:b=2"])
    assert a.sorted_list() == b.sorted_list()
    assert a.sha256_sum() == b.sha256_sum()


def test_labels_sha_differs():
    a = Labels.from_model(["k8s:a=1"])
    b = Labels.from_model(["k8s:a=2"])
    assert a.sha256_sum() != b.sha256_sum()


def test_label_array_contains():
    arr = LabelArray.parse("tag1", "tag2")
    assert arr.contains(LabelArray.parse("tag1"))
    assert arr.contains(LabelArray.parse("tag1", "tag2"))
    assert not arr.contains(LabelArray.parse("tag3"))
    assert arr.contains(LabelArray())  # empty needed -> True


def test_cidr_labels_expand_all_prefixes():
    arr = get_cidr_labels("10.1.1.0/24")
    keys = [l.key for l in arr if l.source == lbl.SOURCE_CIDR]
    assert len(keys) == 25  # /0 .. /24
    assert "10-1-1-0-24" in keys
    assert "0-0-0-0-0" in keys
    # world label included
    assert any(l.source == lbl.SOURCE_RESERVED and l.key == "world"
               for l in arr)


def test_cidr_label_matching_covering_prefix():
    # An IP's expanded labels include every covering prefix, so a policy
    # selector over a broader CIDR label matches the narrower identity.
    ip_labels = get_cidr_labels("10.1.1.7/32")
    want = ip_to_cidr_label("10.1.0.0/16")
    assert any(l.key == want.key and l.source == want.source
               for l in ip_labels)
