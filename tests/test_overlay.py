"""Overlay/tunnel datapath: encap with identity in the tunnel key,
decap deriving identity from it.

Reference semantics being mirrored:
  * bpf/lib/encap.h encap_and_redirect — egress packets to a remote
    pod CIDR leave encapsulated to the peer node's tunnel endpoint with
    the sending endpoint's security identity as the tunnel id, emitting
    TRACE_TO_OVERLAY;
  * bpf/bpf_overlay.c:151 from-overlay — decapsulated packets take
    their source identity from the tunnel key, not the ipcache;
  * pkg/maps/tunnel — node manager programs pod-CIDR -> node-IP.

The e2e test runs two real agent processes sharing a TCP kvstore: node
discovery programs the sender's device tunnel LPM, the sender's
datapath produces the encap decision, and the wire packet is fed to
the receiver's datapath as from-overlay traffic whose verdict uses the
tunnel-carried identity (a wrong tunnel identity is denied even though
the receiver's ipcache would have allowed the sender's address).
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from cilium_tpu.compiler.lpm import compile_lpm, ipv4_to_u32
from cilium_tpu.compiler.policy_tables import compile_endpoints
from cilium_tpu.datapath.engine import Datapath, make_full_batch
from cilium_tpu.datapath.events import (DROP_POLICY, TRACE_TO_LXC,
                                        TRACE_TO_OVERLAY)
from cilium_tpu.kvstore.server import KVStoreServer
from cilium_tpu.policy.mapstate import (EGRESS, INGRESS, PolicyKey,
                                        PolicyMapState, PolicyMapStateEntry)

HERE = os.path.dirname(os.path.abspath(__file__))


def _dp_with_tunnel():
    """One endpoint (slot 0, identity 5001) allowed egress to identity
    300 on 8080; tunnel map: 10.2.0.0/16 -> 192.168.0.2."""
    st = PolicyMapState()
    st[PolicyKey(identity=300, dest_port=8080, nexthdr=6,
                 direction=EGRESS)] = PolicyMapStateEntry()
    st[PolicyKey(identity=4242, dest_port=80, nexthdr=6,
                 direction=INGRESS)] = PolicyMapStateEntry()
    dp = Datapath(ct_slots=1 << 8, ct_probe=4)
    dp.load_policy([st], revision=1,
                   ipcache_prefixes={"10.2.0.0/16": 300,
                                     "10.1.0.0/16": 301})
    dp.load_tunnel({"10.2.0.0/16": ipv4_to_u32("192.168.0.2")})
    dp.set_endpoint_identity(0, 5001)
    return dp


def test_egress_encap_carries_identity_in_tunnel_key():
    dp = _dp_with_tunnel()
    batch = make_full_batch(endpoint=[0, 0], saddr=["10.1.0.5"] * 2,
                            daddr=["10.2.3.4", "10.1.0.9"],
                            sport=[1111, 1112], dport=[8080, 8080],
                            direction=[1, 1])
    verdict, event, identity, nat = dp.process(batch, now=100)
    verdict = np.asarray(verdict)
    event = np.asarray(event)
    # packet 0: allowed egress to the remote pod CIDR -> encap to the
    # peer node with the endpoint's own identity in the tunnel key
    assert verdict[0] == 0
    assert event[0] == TRACE_TO_OVERLAY
    assert np.asarray(nat.tunnel_ep).astype(np.uint32)[0] == \
        ipv4_to_u32("192.168.0.2")
    assert np.asarray(nat.tunnel_id)[0] == 5001
    # packet 1: local destination (no tunnel entry) -> no encap; it is
    # dropped by policy (10.1/16 resolves to identity 301, not allowed)
    assert np.asarray(nat.tunnel_ep)[1] == 0
    assert np.asarray(nat.tunnel_id)[1] == 0
    assert event[1] != TRACE_TO_OVERLAY


def test_denied_or_proxied_egress_does_not_encap():
    st = PolicyMapState()
    # proxy redirect for 300:9090
    st[PolicyKey(identity=300, dest_port=9090, nexthdr=6,
                 direction=EGRESS)] = PolicyMapStateEntry(proxy_port=12345)
    dp = Datapath(ct_slots=1 << 8, ct_probe=4)
    dp.load_policy([st], revision=1,
                   ipcache_prefixes={"10.2.0.0/16": 300})
    dp.load_tunnel({"10.2.0.0/16": ipv4_to_u32("192.168.0.2")})
    dp.set_endpoint_identity(0, 5001)
    batch = make_full_batch(endpoint=[0, 0], saddr=["10.1.0.5"] * 2,
                            daddr=["10.2.3.4", "10.2.3.4"],
                            sport=[2222, 2223], dport=[9090, 7],
                            direction=[1, 1])
    verdict, event, identity, nat = dp.process(batch, now=100)
    verdict = np.asarray(verdict)
    # packet 0 redirects to the proxy: not encapped here (the proxied
    # flow re-enters the datapath after L7); packet 1 is denied
    assert verdict[0] == 12345
    assert verdict[1] < 0
    assert (np.asarray(nat.tunnel_ep) == 0).all()


def test_decap_identity_from_tunnel_key_beats_ipcache():
    """from-overlay ingress: the tunnel id decides the verdict even
    when the ipcache would resolve the address differently
    (bpf_overlay.c:151)."""
    dp = _dp_with_tunnel()
    # ingress allowed only from identity 4242 on port 80.  The source
    # address resolves to 301 via ipcache — which is NOT allowed — so
    # an allow can only come from the tunnel-carried identity.
    batch = make_full_batch(
        endpoint=[0, 0], saddr=["10.1.0.7", "10.1.0.7"],
        daddr=["10.2.9.9", "10.2.9.9"], sport=[3333, 3334],
        dport=[80, 80], direction=[0, 0],
        from_overlay=[1, 1], tunnel_id=[4242, 2])
    verdict, event, identity, _nat = dp.process(batch, now=100)
    verdict = np.asarray(verdict)
    identity = np.asarray(identity)
    assert identity[0] == 4242 and verdict[0] == 0
    # wrong tunnel identity (WORLD): denied, though same source addr
    assert identity[1] == 2 and verdict[1] < 0


def test_non_overlay_batch_unchanged():
    """Batches without overlay fields behave exactly as before."""
    dp = _dp_with_tunnel()
    batch = make_full_batch(endpoint=[0], saddr=["10.1.0.7"],
                            daddr=["10.9.9.9"], sport=[4444],
                            dport=[80], direction=[0])
    assert batch.from_overlay is None
    verdict, event, identity, nat = dp.process(batch, now=100)
    # identity resolves via ipcache as before (10.1/16 -> 301), which
    # the ingress policy (4242:80 only) denies
    assert np.asarray(identity)[0] == 301
    assert np.asarray(verdict)[0] < 0
    assert np.asarray(nat.tunnel_ep)[0] == 0


def test_node_manager_programs_device_tunnel_table():
    from cilium_tpu.node import Node, NodeAddress, NodeManager
    dp = Datapath(ct_slots=1 << 8, ct_probe=4)
    st = PolicyMapState()
    st[PolicyKey(identity=300, dest_port=8080, nexthdr=6,
                 direction=EGRESS)] = PolicyMapStateEntry()
    dp.load_policy([st], revision=1,
                   ipcache_prefixes={"10.2.0.0/16": 300})
    dp.set_endpoint_identity(0, 7007)
    mgr = NodeManager("default/local", datapath=dp)
    mgr.node_updated(Node(name="peer",
                          addresses=[NodeAddress("InternalIP",
                                                 "192.168.44.2")],
                          ipv4_alloc_cidr="10.2.0.0/16"))
    assert list(dp.tunnel_prefixes) == ["10.2.0.0/16"]
    assert (dp.tunnel_prefixes["10.2.0.0/16"] & 0xFFFFFFFF) == \
        ipv4_to_u32("192.168.44.2")
    batch = make_full_batch(endpoint=[0], saddr=["10.1.0.5"],
                            daddr=["10.2.3.4"], sport=[5555],
                            dport=[8080], direction=[1])
    _v, event, _i, nat = dp.process(batch, now=100)
    assert np.asarray(event)[0] == TRACE_TO_OVERLAY
    assert np.asarray(nat.tunnel_id)[0] == 7007
    # node deletion tears the tunnel entry down
    mgr.node_deleted("default/peer")
    assert dp.tunnel_prefixes == {}
    _v, event, _i, nat = dp.process(batch, now=101)
    assert np.asarray(nat.tunnel_ep)[0] == 0


# --------------------------------------------------- cross-process e2e

def _read_json_line(stream, timeout=60.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        line = stream.readline()
        if line:
            return json.loads(line)
    raise TimeoutError("no JSON line from subprocess")


def test_two_node_overlay_exchange():
    """Two agent processes, one kvstore: the sender encaps with its
    identity in the tunnel key; the receiver's verdict follows the
    tunnel identity — allowed for the real identity, denied for a
    forged WORLD identity on the very same addresses."""
    server = KVStoreServer(port=0).start()
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    procs = []
    try:
        recv = subprocess.Popen(
            [sys.executable, os.path.join(HERE, "overlay_proc.py"),
             str(server.port), "node-b", "receiver"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True,
            env=env)
        procs.append(recv)
        ready = _read_json_line(recv.stdout)
        assert ready["ready"]

        send = subprocess.Popen(
            [sys.executable, os.path.join(HERE, "overlay_proc.py"),
             str(server.port), "node-a", "sender"],
            stdout=subprocess.PIPE, text=True, env=env)
        procs.append(send)
        wire = _read_json_line(send.stdout)
        # the sender encapped: tunnel endpoint is the receiver's node
        # IP, tunnel id is the sending endpoint's identity
        assert wire["to_overlay"], wire
        assert wire["tunnel_ep"] == "192.168.7.2"
        assert wire["tunnel_id"] == wire["endpoint_identity"] > 0

        # deliver the wire packet to the receiver: allowed via the
        # tunnel-carried identity
        recv.stdin.write(json.dumps({
            "saddr": wire["saddr"], "daddr": wire["daddr"],
            "dport": 80, "tunnel_id": wire["tunnel_id"]}) + "\n")
        recv.stdin.flush()
        out = _read_json_line(recv.stdout)
        assert out["identity_used"] == wire["tunnel_id"]
        assert out["verdict"] == 0, out

        # forged tunnel identity (WORLD) on the same addresses: denied,
        # even though the receiver's ipcache knows the sender's address.
        # Fresh source port — the first packet's allowed flow is in the
        # receiver's conntrack, and established flows (correctly) keep
        # their CT verdict without re-running policy.
        recv.stdin.write(json.dumps({
            "saddr": wire["saddr"], "daddr": wire["daddr"],
            "sport": 40002, "dport": 80, "tunnel_id": 2}) + "\n")
        recv.stdin.flush()
        out2 = _read_json_line(recv.stdout)
        assert out2["identity_used"] == 2
        assert out2["verdict"] < 0, out2

        recv.stdin.write(json.dumps({"op": "quit"}) + "\n")
        recv.stdin.flush()
    finally:
        for p in procs:
            try:
                p.kill()
            except OSError:
                pass
        server.shutdown()
