"""Subprocess agent for cross-process kvstore tests.

Spawned by tests/test_remote_kvstore.py: connects a full Daemon to the
TCP kvstore server, creates endpoints (allocating distributed
identities over the wire), reports state as one JSON line on stdout,
then either exits or sleeps until killed (kill -9 models node death:
the lease stops renewing and the server reaps the session).

Usage: python tests/agent_proc.py <port> <node_name> <mode> <ttl> [backend]
  mode "report": allocate, print, clean shutdown
  mode "sleep":  allocate, print, then sleep forever (parent kills -9)
  backend: "remote" (default, TCP kvstore) or "etcd" (etcd v3 JSON
  protocol against a mini-etcd/real gateway on <port>)
"""

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from cilium_tpu.daemon import Daemon  # noqa: E402
from cilium_tpu.kvstore.remote import RemoteBackend  # noqa: E402
from cilium_tpu.utils.option import DaemonConfig  # noqa: E402


def main() -> None:
    port = int(sys.argv[1])
    node = sys.argv[2]
    mode = sys.argv[3]
    ttl = float(sys.argv[4]) if len(sys.argv) > 4 else 2.0
    backend = sys.argv[5] if len(sys.argv) > 5 else "remote"

    if backend == "etcd":
        from cilium_tpu.kvstore.etcd import EtcdBackend
        kv = EtcdBackend(port=port, lease_ttl=ttl)
    else:
        kv = RemoteBackend(port=port, lease_ttl=ttl)
    d = Daemon(config=DaemonConfig(), kvstore_backend=kv, node_name=node)
    try:
        # two endpoints: one with cluster-shared labels, one node-unique
        ep_shared = d.endpoint_create(
            1, ipv4=f"10.50.{1 if node.endswith('a') else 2}.1",
            labels=["k8s:app=shared-web"])
        ep_unique = d.endpoint_create(
            2, ipv4=f"10.50.{1 if node.endswith('a') else 2}.2",
            labels=[f"k8s:app=only-{node}"])
        # identity allocation is synchronous in endpoint_create;
        # give ipcache kvstore sync a beat, then read the cluster view
        deadline = time.time() + 10.0
        want = {"10.50.1.1", "10.50.2.1"}
        view = {}
        while time.time() < deadline:
            view = {ip: d.ipcache.lookup_by_ip(ip) for ip in want}
            if all(v is not None for v in view.values()):
                break
            time.sleep(0.1)
        print(json.dumps({
            "node": node,
            "shared_identity": ep_shared.security_identity,
            "unique_identity": ep_unique.security_identity,
            "ipcache": {ip: view.get(ip) for ip in sorted(want)},
            "kv_status": kv.status(),
        }), flush=True)
        if mode == "sleep":
            time.sleep(3600)
    finally:
        if mode != "sleep":
            d.shutdown()
            kv.close()


if __name__ == "__main__":
    main()
