"""Auxiliary subsystems: health prober, xDS cache, IPAM, workloads,
bugtool, CNI.
"""

import json
import os
import tarfile
import threading
import time

import pytest

from cilium_tpu.daemon import Daemon
from cilium_tpu.health import HealthProber
from cilium_tpu.ipam import HostScopeIPAM, IPAMError
from cilium_tpu.utils.option import DaemonConfig
from cilium_tpu.workloads import WorkloadWatcher
from cilium_tpu.xds import (TYPE_NETWORK_POLICY, Cache,
                            host_mapping_resources)


# -------------------------------------------------------------------- health

def test_health_prober_sweep_and_node_removal():
    nodes = [("default/n1", "192.168.0.1"), ("default/n2", "192.168.0.2")]
    down = {"192.168.0.2"}

    def probe(kind, ip):
        return (ip not in down, 0.001)

    p = HealthProber(lambda: list(nodes), probe_fn=probe, interval=3600)
    p.probe_once()
    st = p.status()
    assert st["default/n1"]["healthy"]
    assert not st["default/n2"]["healthy"]
    assert p.unhealthy_nodes() == ["default/n2"]
    # node leaves the cluster -> status entry reaped
    nodes.pop(1)
    p.probe_once()
    assert "default/n2" not in p.status()
    # probe exceptions count as failures, don't kill the sweep
    def bad(kind, ip):
        raise OSError("no route")
    p.probe_fn = bad
    p.probe_once()
    assert not p.status()["default/n1"]["healthy"]
    p.shutdown()


# ----------------------------------------------------------------------- xds

def test_xds_versioning_watch_and_ack_barrier():
    cache = Cache()
    w1 = cache.watch(TYPE_NETWORK_POLICY, "proxy-1")
    w2 = cache.watch(TYPE_NETWORK_POLICY, "proxy-2")

    v = cache.set_resources(TYPE_NETWORK_POLICY, {"100": {"policy": 7}})
    assert v == 1
    got = w1.next(timeout=2)
    assert got.version == 1 and got.resources["100"]["policy"] == 7

    comp = cache.wait_for_acks(TYPE_NETWORK_POLICY, 1)
    assert not comp.completed
    w1.ack(1)
    assert not comp.completed   # proxy-2 hasn't acked
    w2.ack(1)
    assert comp.completed       # barrier released

    # upsert bumps version; watcher sees only the newest
    cache.upsert(TYPE_NETWORK_POLICY, "200", {"policy": 8})
    cache.delete(TYPE_NETWORK_POLICY, "100")
    got = w1.next(timeout=2)
    assert got.version == 3
    assert set(got.resources) == {"200"}
    # ack of a later version satisfies barriers on earlier ones
    comp2 = cache.wait_for_acks(TYPE_NETWORK_POLICY, 2)
    w1.ack(3)
    w2.ack(3)
    assert comp2.completed
    # nacks are recorded
    w1.nack(3, "bad resource")
    assert cache.nacks[0][1] == "proxy-1"
    # no watchers for a type => barrier completes immediately
    assert cache.wait_for_acks("type/none", 1).completed


def test_xds_watch_blocks_until_update():
    cache = Cache()
    w = cache.watch(TYPE_NETWORK_POLICY, "p")
    assert w.next(timeout=0.05) is None
    result = {}

    def consume():
        result["vr"] = w.next(timeout=5)

    t = threading.Thread(target=consume)
    t.start()
    time.sleep(0.05)
    cache.set_resources(TYPE_NETWORK_POLICY, {"a": 1})
    t.join(timeout=5)
    assert result["vr"].version == 1


def test_host_mapping_resources_shape():
    res = host_mapping_resources({"10.0.0.1": 300, "10.0.0.2": 300,
                                  "10.0.0.3": 400})
    assert res["300"]["host_addresses"] == ["10.0.0.1", "10.0.0.2"]
    assert res["400"]["policy"] == 400


# ---------------------------------------------------------------------- ipam

def test_ipam_allocate_release_cycle():
    ipam = HostScopeIPAM("10.5.0.0/29", reserve_first=2)  # 8 addrs
    # usable: .2 .3 .4 .5 .6 (network .0, reserved .1, broadcast .7)
    ips = [ipam.allocate_next(owner=f"c{i}") for i in range(5)]
    assert ips[0] == "10.5.0.2"
    with pytest.raises(IPAMError):
        ipam.allocate_next()
    assert ipam.release("10.5.0.4")
    assert ipam.allocate_next() == "10.5.0.4"
    assert len(ipam) == 5
    # double release is a no-op
    assert ipam.release("10.5.0.4")
    assert not ipam.release("10.5.0.4")
    assert len(ipam) == 4


def test_ipam_allocate_specific_for_restore():
    ipam = HostScopeIPAM("10.5.0.0/24")
    assert ipam.allocate_ip("10.5.0.77", owner="restored") == "10.5.0.77"
    with pytest.raises(IPAMError):
        ipam.allocate_ip("10.5.0.77")
    with pytest.raises(IPAMError):
        ipam.allocate_ip("10.9.0.1")  # outside the pod CIDR
    # allocate_next skips the restored address when it reaches it
    seen = {ipam.allocate_next() for _ in range(100)}
    assert "10.5.0.77" not in seen


# ------------------------------------------------------------------ workloads

def test_workload_watcher_lifecycle():
    d = Daemon(config=DaemonConfig())
    ipam = HostScopeIPAM("10.8.0.0/24")
    w = WorkloadWatcher(d, ipam=ipam)
    try:
        ep_id = w.on_start({"id": "abc123", "name": "web-1",
                            "labels": {"app": "web"}})
        assert d.wait_for_quiesce(10)
        ep = d.endpoints.lookup(ep_id)
        assert ep is not None
        assert ep.container_name == "web-1"
        assert ep.ipv4.startswith("10.8.0.")
        assert d.ipcache.lookup_by_ip(ep.ipv4) == ep.security_identity
        first_identity = ep.security_identity

        # label change on restart -> same endpoint, new identity
        w.on_start({"id": "abc123", "name": "web-1",
                    "labels": {"app": "web", "tier": "frontend"}})
        assert d.wait_for_quiesce(10)
        assert w.endpoint_of("abc123") == ep_id
        assert d.endpoints.lookup(ep_id).security_identity != \
            first_identity

        ip = ep.ipv4
        assert w.on_stop("abc123")
        assert d.endpoints.lookup(ep_id) is None
        assert len(ipam) == 0  # IP returned to the pool
        assert d.ipcache.lookup_by_ip(ip) is None
        assert not w.on_stop("abc123")  # idempotent
    finally:
        d.shutdown()


# -------------------------------------------------------------------- bugtool

def test_bugtool_archives_daemon_state(tmp_path):
    from cilium_tpu.bugtool import collect
    d = Daemon(config=DaemonConfig())
    try:
        d.endpoint_create(1, ipv4="10.0.0.1", labels=["k8s:a=b"])
        assert d.wait_for_quiesce(10)
        out = str(tmp_path / "bug.tar.gz")
        path = collect(d, out)
        assert path == out
        with tarfile.open(path) as tar:
            names = [os.path.basename(m.name) for m in tar.getmembers()]
            assert "status.json" in names
            assert "endpoints.json" in names
            assert "metrics.txt" in names
            member = [m for m in tar.getmembers()
                      if m.name.endswith("endpoints.json")][0]
            eps = json.load(tar.extractfile(member))
            assert eps[0]["id"] == 1
    finally:
        d.shutdown()


# ------------------------------------------------------------------------ cni

def test_cni_add_del_via_rest(tmp_path):
    from cilium_tpu.cli import Client
    from cilium_tpu.cni import cni_add, cni_del, _endpoint_id_for
    from cilium_tpu.daemon.rest import APIServer
    d = Daemon(config=DaemonConfig())
    server = APIServer(d).start()
    try:
        c = Client(server.base_url)
        result = cni_add(c, "container-xyz", netns="/proc/1/ns/net",
                         config={"ip": "10.0.0.42",
                                 "labels": {"app": "db"}})
        assert result["cniVersion"] == "0.3.1"
        assert result["ips"][0]["address"] == "10.0.0.42/32"
        ep_id = _endpoint_id_for("container-xyz")
        ep = d.endpoints.lookup(ep_id)
        assert ep is not None and ep.ipv4 == "10.0.0.42"
        assert any("app=db" in str(l) for l in ep.labels.to_array())
        assert cni_del(c, "container-xyz")
        assert d.endpoints.lookup(ep_id) is None
        assert not cni_del(c, "container-xyz")  # idempotent
    finally:
        server.shutdown()
        d.shutdown()


# --------------------------------------------- review-regression coverage

def test_np_match_expressions_preserved():
    from cilium_tpu.k8s import parse_network_policy
    from cilium_tpu.labels import LabelArray
    np_obj = {
        "metadata": {"name": "expr-np", "namespace": "prod"},
        "spec": {
            "podSelector": {},
            "ingress": [{"from": [{"podSelector": {"matchExpressions": [
                {"key": "role", "operator": "In",
                 "values": ["frontend", "edge"]}]}}]}],
        },
    }
    rules = parse_network_policy(np_obj)
    sel = rules[0].ingress[0].from_endpoints[0]
    fe = LabelArray.parse_select("k8s:role=frontend",
                                 "k8s:io.kubernetes.pod.namespace=prod")
    other = LabelArray.parse_select("k8s:role=backend",
                                    "k8s:io.kubernetes.pod.namespace=prod")
    assert sel.matches(fe)
    assert not sel.matches(other)  # expressions must not be dropped


def test_watcher_toservices_allocates_cidr_identities():
    d = Daemon(config=DaemonConfig())
    from cilium_tpu.k8s import K8sWatcher
    w = K8sWatcher(d)
    try:
        w.on_cnp("added", {
            "metadata": {"name": "svc-pol", "namespace": "prod"},
            "spec": {"endpointSelector": {"matchLabels": {"app": "web"}},
                     "egress": [{"toServices": [{"k8sService": {
                         "serviceName": "db", "namespace": "prod"}}]}]}})
        w.on_endpoints("added", {
            "metadata": {"name": "db", "namespace": "prod"},
            "subsets": [{"addresses": [{"ip": "10.0.0.50"}]}]})
        # the backend /32 received a CIDR identity + ipcache entry
        assert d.ipcache.lookup_by_ip("10.0.0.50/32") is not None
        # backend change releases the old prefix and maps the new one
        w.on_endpoints("added", {
            "metadata": {"name": "db", "namespace": "prod"},
            "subsets": [{"addresses": [{"ip": "10.0.0.51"}]}]})
        assert d.ipcache.lookup_by_ip("10.0.0.51/32") is not None
        assert d.ipcache.lookup_by_ip("10.0.0.50/32") is None
    finally:
        d.shutdown()


def test_watcher_named_target_port_survives():
    d = Daemon(config=DaemonConfig())
    from cilium_tpu.k8s import K8sWatcher
    w = K8sWatcher(d)
    try:
        w.on_endpoints("added", {
            "metadata": {"name": "web", "namespace": "default"},
            "subsets": [{"addresses": [{"ip": "10.0.0.3"}]}]})
        w.on_service("added", {
            "metadata": {"name": "web", "namespace": "default"},
            "spec": {"clusterIP": "10.96.0.2",
                     "ports": [{"port": 80, "targetPort": "http"}]}})
        svc = d.datapath.lb.services()[0]
        assert svc.backends[0].port == 80  # fell back to service port
    finally:
        d.shutdown()


def test_json_import_cannot_smuggle_generated_flag():
    from cilium_tpu.policy.api import PolicyError
    from cilium_tpu.policy.jsonio import rules_from_json
    bad = json.dumps([{
        "endpointSelector": {"matchLabels": {"a": "b"}},
        "egress": [{"toEndpoints": [{"matchLabels": {"c": "d"}}],
                    "toCIDRSet": [{"cidr": "10.0.0.0/8",
                                   "generated": True}]}]}])
    rules = rules_from_json(bad)
    with pytest.raises(PolicyError):
        rules[0].sanitize()  # exclusivity check must still fire


def test_xds_no_deadlock_upsert_vs_next():
    """Concurrent upserts and blocking next() must not deadlock."""
    cache = Cache()
    w = cache.watch(TYPE_NETWORK_POLICY, "p")
    stop = threading.Event()
    errors = []

    def producer():
        for i in range(200):
            cache.upsert(TYPE_NETWORK_POLICY, f"r{i % 5}", {"v": i})
        stop.set()

    def consumer():
        try:
            while not stop.is_set():
                vr = w.next(timeout=0.01)
                if vr:
                    w.ack(vr.version)
        except Exception as e:
            errors.append(e)

    t1 = threading.Thread(target=producer)
    t2 = threading.Thread(target=consumer)
    t1.start(); t2.start()
    t1.join(timeout=20); t2.join(timeout=20)
    assert not t1.is_alive() and not t2.is_alive()
    assert not errors
    assert cache.get(TYPE_NETWORK_POLICY).version == 200


def test_cni_add_idempotent():
    from cilium_tpu.cli import Client
    from cilium_tpu.cni import cni_add, _endpoint_id_for
    from cilium_tpu.daemon.rest import APIServer
    d = Daemon(config=DaemonConfig())
    server = APIServer(d).start()
    try:
        c = Client(server.base_url)
        r1 = cni_add(c, "retry-me", config={"ip": "10.0.0.9"})
        r2 = cni_add(c, "retry-me", config={"ip": "10.0.0.9"})  # retried
        assert r1 == r2
        assert d.endpoints.lookup(_endpoint_id_for("retry-me")) is not None
    finally:
        server.shutdown()
        d.shutdown()
