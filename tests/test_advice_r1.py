"""Round-1 advisor regression tests (ADVICE.md).

1. memcached binary quiet-opcode / unknown-opcode fail-open (high)
2. Datapath.refresh_policy vs DeviceTableManager geometry race (medium)
3. translate_to_services wiping other services' generated CIDRs (medium)
4. memcached unknown text command fail-open (low)
"""

import struct
import threading

import numpy as np
import pytest

from cilium_tpu.l7.parser import Instance, Op, PortRuleL7


def rules(*dicts):
    return [PortRuleL7.from_dict(d) for d in dicts]


def _mc(inst, l7, conn_id=1):
    assert inst.on_new_connection("memcache", conn_id, True, 300, 400,
                                  l7_rules=l7)
    return conn_id


def bin_frame(opcode: int, key: bytes, extras: bytes = b"") -> bytes:
    body = extras + key
    return struct.pack(">BBHBBHIIQ", 0x80, opcode, len(key),
                       len(extras), 0, 0, len(body), 7, 0) + body


# --------------------------------------------------- memcached fail-open

QUIET_MUTATIONS = {
    0x11: "set", 0x12: "add", 0x13: "replace", 0x14: "delete",
    0x15: "incr", 0x16: "decr", 0x19: "append", 0x1A: "prepend",
}


def test_quiet_binary_opcodes_enforced():
    """SetQ/AddQ/... must hit the same ACL as their loud variants —
    the round-1 map omitted them, so `setq` bypassed the policy."""
    inst = Instance()
    cid = _mc(inst, rules({"command": "get", "key": "ok*"}))
    for opcode in QUIET_MUTATIONS:
        extras = b"\x00" * 8 if opcode in (0x11, 0x12, 0x13) else b""
        ops = inst.on_data(cid, False, False,
                           bin_frame(opcode, b"ok:1", extras))
        assert ops[0].op == Op.DROP, hex(opcode)
        assert ops[1].op == Op.INJECT


def test_quiet_opcodes_allowed_when_rule_matches():
    inst = Instance()
    cid = _mc(inst, rules({"command": "set", "key": "sess:*"}))
    # SetQ on an allowed key passes
    ops = inst.on_data(cid, False, False,
                       bin_frame(0x11, b"sess:1", b"\x00" * 8))
    assert [o.op for o in ops] == [Op.PASS]


def test_unknown_binary_opcode_fails_closed_with_rules():
    inst = Instance()
    cid = _mc(inst, rules({"command": "get", "key": "*"}))
    ops = inst.on_data(cid, False, False, bin_frame(0x7F, b"k"))
    assert ops[0].op == Op.DROP and ops[1].op == Op.INJECT
    # status = access denied in the injected response
    status = struct.unpack(">BBHBBH", ops[1].data[:8])[5]
    assert status == 0x08


def test_unknown_binary_opcode_passes_without_rules():
    inst = Instance()
    cid = _mc(inst, [])
    ops = inst.on_data(cid, False, False, bin_frame(0x7F, b"k"))
    assert [o.op for o in ops] == [Op.PASS]


def test_unknown_text_command_fails_closed_with_rules():
    """Meta commands (mg/ms) must not bypass the key ACL.  The parser
    cannot know an unknown command's payload length, so it fails the
    parse (connection reset) rather than dropping just the line and
    desyncing on the payload."""
    inst = Instance()
    cid = _mc(inst, rules({"command": "get", "key": "*"}))
    ops = inst.on_data(cid, False, False, b"ms somekey 5\r\nhello\r\n")
    assert ops[0].op == Op.ERROR
    inst2 = Instance()
    cid2 = _mc(inst2, [], conn_id=2)
    ops = inst2.on_data(cid2, False, False, b"mg somekey v\r\n")
    assert ops[0].op == Op.PASS


# --------------------------------- table-manager snapshot vs refresh race

def test_snapshot_is_atomic_under_concurrent_sync():
    """snapshot() must return geometry consistent with its tensors even
    while another thread grows/syncs the table stack."""
    from cilium_tpu.endpoint.tables import DeviceTableManager
    from cilium_tpu.policy.mapstate import (INGRESS, PolicyKey,
                                            PolicyMapState,
                                            PolicyMapStateEntry)
    mgr = DeviceTableManager(initial_endpoints=2, initial_slots=8)
    for ep in range(2):
        mgr.attach(ep)
    stop = threading.Event()
    errors = []

    def churn():
        ident = 256
        while not stop.is_set():
            st = PolicyMapState()
            for _ in range(20):
                st[PolicyKey(identity=ident, dest_port=ident % 60000,
                             nexthdr=6, direction=INGRESS)] = \
                    PolicyMapStateEntry()
                ident += 1
            try:
                mgr.sync_endpoint(ident % 2, st, revision=ident)
            except Exception as e:  # pragma: no cover
                errors.append(e)
                return

    t = threading.Thread(target=churn, daemon=True)
    t.start()
    try:
        for _ in range(200):
            (capacity, slots, max_probe, _gen), (kid, kmeta, val) = \
                mgr.snapshot()
            assert kid.shape == (capacity, slots)
            assert kmeta.shape == (capacity, slots)
            assert val.shape == (capacity, slots)
            assert max_probe >= 1
    finally:
        stop.set()
        t.join(timeout=10)
    assert not errors


def test_refresh_policy_uses_snapshot_geometry():
    """refresh_policy must jit/install from one consistent snapshot; a
    grow between geometry read and tensor fetch used to install
    reshaped tensors under a stale step."""
    from cilium_tpu.datapath.engine import Datapath, make_full_batch
    from cilium_tpu.endpoint.tables import DeviceTableManager
    from cilium_tpu.policy.mapstate import (EGRESS, PolicyKey,
                                            PolicyMapState,
                                            PolicyMapStateEntry)
    mgr = DeviceTableManager(initial_endpoints=2, initial_slots=8)
    mgr.attach(0)
    dp = Datapath(ct_slots=64, ct_probe=4)
    dp.use_table_manager(mgr, ipcache_prefixes={"10.0.0.0/8": 300})
    st = PolicyMapState()
    st[PolicyKey(identity=300, dest_port=80, nexthdr=6,
                 direction=EGRESS)] = PolicyMapStateEntry()
    mgr.sync_endpoint(0, st, revision=1)
    assert dp.refresh_policy(revision=1) in (True, False)
    batch = make_full_batch(endpoint=[0], saddr=["10.1.1.1"],
                            daddr=["10.0.0.5"], sport=[1234], dport=[80],
                            direction=[1])
    verdict, _ev, _ident, _nat = dp.process(batch, now=1000)
    assert int(np.asarray(verdict)[0]) >= 0  # allowed
    # force a grow (more entries than slots allow) and refresh again
    big = PolicyMapState()
    for i in range(300):
        big[PolicyKey(identity=300 + i, dest_port=80, nexthdr=6,
                      direction=EGRESS)] = PolicyMapStateEntry()
    mgr.sync_endpoint(0, big, revision=2)
    assert dp.refresh_policy(revision=2) is True  # re-jit on geometry
    verdict, _ev, _ident, _nat = dp.process(batch, now=1001)
    assert int(np.asarray(verdict)[0]) >= 0


# ------------------------------------ ToServices translation per-service

def test_translate_preserves_other_services_cidrs():
    from cilium_tpu.k8s import translate_to_services
    from cilium_tpu.policy.api import (EgressRule, EndpointSelector,
                                       K8sServiceNamespace, Rule, Service)
    rule = Rule(
        endpoint_selector=EndpointSelector.parse("app=x"),
        egress=[EgressRule(to_services=[
            Service(k8s_service=K8sServiceNamespace(
                service_name="a", namespace="prod")),
            Service(k8s_service=K8sServiceNamespace(
                service_name="b", namespace="prod"))])])
    translate_to_services([rule], "a", "prod", ["10.0.0.1"])
    translate_to_services([rule], "b", "prod", ["10.0.1.1"])
    cidrs = sorted(c.cidr for c in rule.egress[0].to_cidr_set)
    assert cidrs == ["10.0.0.1/32", "10.0.1.1/32"]
    # service a's backends change: b's generated entry must survive
    translate_to_services([rule], "a", "prod", ["10.0.0.2"],
                          old_backend_ips=["10.0.0.1"])
    cidrs = sorted(c.cidr for c in rule.egress[0].to_cidr_set)
    assert cidrs == ["10.0.0.2/32", "10.0.1.1/32"]
    # a scales to zero: only a's entry removed
    translate_to_services([rule], "a", "prod", [],
                          old_backend_ips=["10.0.0.2"])
    cidrs = [c.cidr for c in rule.egress[0].to_cidr_set]
    assert cidrs == ["10.0.1.1/32"]


def test_watcher_endpoints_event_keeps_sibling_service():
    from cilium_tpu.daemon import Daemon
    from cilium_tpu.k8s import K8sWatcher
    from cilium_tpu.policy.api import (EgressRule, EndpointSelector,
                                       K8sServiceNamespace, Rule, Service)
    from cilium_tpu.utils.option import DaemonConfig
    d = Daemon(config=DaemonConfig())
    w = K8sWatcher(d)
    try:
        rule = Rule(
            endpoint_selector=EndpointSelector.parse("app=x"),
            egress=[EgressRule(to_services=[
                Service(k8s_service=K8sServiceNamespace(
                    service_name="a", namespace="ns")),
                Service(k8s_service=K8sServiceNamespace(
                    service_name="b", namespace="ns"))])])
        d.policy_add([rule])

        def ep_obj(name, ips):
            return {"metadata": {"name": name, "namespace": "ns"},
                    "subsets": [{"addresses": [{"ip": ip} for ip in ips]}]}

        w.on_endpoints("added", ep_obj("a", ["10.8.0.1"]))
        w.on_endpoints("added", ep_obj("b", ["10.8.1.1"]))
        # an Endpoints update for a must not wipe b's backends
        w.on_endpoints("modified", ep_obj("a", ["10.8.0.2"]))
        live = d.repo.rules[0]
        cidrs = sorted(c.cidr for c in live.egress[0].to_cidr_set)
        assert cidrs == ["10.8.0.2/32", "10.8.1.1/32"]
    finally:
        d.shutdown()


def test_shared_backend_ip_survives_sibling_scaledown():
    """Two services selecting the same pod IP: one service scaling to
    zero must not delete the IP while the other still owns it."""
    from cilium_tpu.daemon import Daemon
    from cilium_tpu.k8s import K8sWatcher
    from cilium_tpu.policy.api import (EgressRule, EndpointSelector,
                                       K8sServiceNamespace, Rule, Service)
    from cilium_tpu.utils.option import DaemonConfig
    d = Daemon(config=DaemonConfig())
    w = K8sWatcher(d)
    try:
        rule = Rule(
            endpoint_selector=EndpointSelector.parse("app=x"),
            egress=[EgressRule(to_services=[
                Service(k8s_service=K8sServiceNamespace(
                    service_name="a", namespace="ns")),
                Service(k8s_service=K8sServiceNamespace(
                    service_name="b", namespace="ns"))])])
        d.policy_add([rule])

        def ep_obj(name, ips):
            return {"metadata": {"name": name, "namespace": "ns"},
                    "subsets": [{"addresses": [{"ip": i} for i in ips]}]}

        shared = "10.9.0.1"
        w.on_endpoints("added", ep_obj("a", [shared]))
        w.on_endpoints("added", ep_obj("b", [shared, "10.9.0.2"]))
        # a scales to zero; b still selects the shared pod
        w.on_endpoints("modified", ep_obj("a", []))
        live = d.repo.rules[0]
        cidrs = sorted(c.cidr for c in live.egress[0].to_cidr_set)
        assert cidrs == ["10.9.0.1/32", "10.9.0.2/32"]
    finally:
        d.shutdown()
