"""Repository verdict tests — mirrors reference pkg/policy/repository_test.go
(TestAddSearchDelete, TestCanReachIngress/Egress, wildcard tests) and the
FromRequires precedence matrices.
"""

import pytest

from cilium_tpu.labels import LabelArray
from cilium_tpu.policy.api import (CIDRRule, Decision, EgressRule,
                                   EndpointSelector, IngressRule, L7Rules,
                                   PolicyError, PortProtocol, PortRule,
                                   PortRuleHTTP, PortRuleKafka, Rule)
from cilium_tpu.policy.repository import Repository
from cilium_tpu.policy.trace import Port, SearchContext, traced_context


def es(*labels):
    return EndpointSelector.parse(*labels)


def ctx(frm, to, dports=None):
    return SearchContext(from_labels=LabelArray.parse_select(*frm),
                         to_labels=LabelArray.parse_select(*to),
                         dports=list(dports or []))


def test_add_search_delete():
    repo = Repository()
    tag1 = LabelArray.parse("tag1", "tag2")
    tag2 = LabelArray.parse("tag3")
    rule1 = Rule(endpoint_selector=es("foo"), labels=tag1)
    rule2 = Rule(endpoint_selector=es("bar"), labels=tag1)
    rule3 = Rule(endpoint_selector=es("bar"), labels=tag2)

    assert repo.revision == 1
    assert repo.add(rule1) == 2
    assert repo.add(rule2) == 3
    assert repo.search(tag2) == []
    assert repo.add(rule3) == 4
    assert repo.search(tag1) == [rule1, rule2]
    assert repo.search(tag2) == [rule3]

    rev, n = repo.delete_by_labels(tag1)
    assert (rev, n) == (5, 2)
    rev, n = repo.delete_by_labels(tag1)
    assert (rev, n) == (5, 0)
    assert repo.search(tag2) == [rule3]
    rev, n = repo.delete_by_labels(tag2)
    assert (rev, n) == (6, 1)
    assert repo.search(tag2) == []


def test_empty_rule_rejected():
    repo = Repository()
    with pytest.raises(PolicyError):
        repo.add(Rule(endpoint_selector=None))


def _load_can_reach_rules(repo):
    tag1 = LabelArray.parse("tag1")
    repo.add(Rule(endpoint_selector=es("bar"), labels=tag1, ingress=[
        IngressRule(from_endpoints=[es("foo")])]))
    repo.add(Rule(endpoint_selector=es("groupA"), labels=tag1, ingress=[
        IngressRule(from_requires=[es("groupA")])]))
    repo.add(Rule(endpoint_selector=es("bar2"), labels=tag1, ingress=[
        IngressRule(from_endpoints=[es("foo")])]))


def test_can_reach_ingress_matrix():
    """Reference: repository_test.go:193 TestCanReachIngress."""
    repo = Repository()
    foo_to_bar = ctx(["foo"], ["bar"])
    assert repo.can_reach_ingress(foo_to_bar) == Decision.UNDECIDED
    assert repo.allows_ingress(foo_to_bar) == Decision.DENIED

    _load_can_reach_rules(repo)

    assert repo.allows_ingress(ctx(["foo"], ["bar"])) == Decision.ALLOWED
    assert repo.allows_ingress(ctx(["foo"], ["bar2"])) == Decision.ALLOWED
    # foo inside groupA => OK (requirement satisfied)
    assert repo.allows_ingress(
        ctx(["foo", "groupA"], ["bar", "groupA"])) == Decision.ALLOWED
    # groupB can't talk to groupA => denied by FromRequires
    assert repo.allows_ingress(
        ctx(["foo", "groupB"], ["bar", "groupA"])) == Decision.DENIED
    # no restriction on groupB
    assert repo.allows_ingress(
        ctx(["foo", "groupB"], ["bar", "groupB"])) == Decision.ALLOWED
    # no rule for bar3
    assert repo.allows_ingress(ctx(["foo"], ["bar3"])) == Decision.DENIED


def test_can_reach_egress_matrix():
    """Reference: repository_test.go:287 TestCanReachEgress (mirrored)."""
    repo = Repository()
    tag1 = LabelArray.parse("tag1")
    repo.add(Rule(endpoint_selector=es("foo"), labels=tag1, egress=[
        EgressRule(to_endpoints=[es("bar")])]))
    repo.add(Rule(endpoint_selector=es("groupA"), labels=tag1, egress=[
        EgressRule(to_requires=[es("groupA")])]))

    assert repo.allows_egress(ctx(["foo"], ["bar"])) == Decision.ALLOWED
    assert repo.allows_egress(
        ctx(["foo", "groupA"], ["bar", "groupA"])) == Decision.ALLOWED
    # egress from groupA member to non-groupA => denied by ToRequires
    assert repo.allows_egress(
        ctx(["foo", "groupA"], ["bar", "groupB"])) == Decision.DENIED
    assert repo.allows_egress(ctx(["baz"], ["bar"])) == Decision.DENIED


def test_from_requires_denies_even_with_allow():
    """FromRequires failure takes precedence over a matching allow in the
    same rule (reference: rule.go:352 comment — separate loops)."""
    repo = Repository()
    repo.add(Rule(endpoint_selector=es("bar"), ingress=[
        IngressRule(from_requires=[es("trusted")],
                    from_endpoints=[es("foo")])]))
    # foo without trusted: the allow in the same rule must NOT win.
    assert repo.allows_ingress(ctx(["foo"], ["bar"])) == Decision.DENIED
    assert repo.allows_ingress(
        ctx(["foo", "trusted"], ["bar"])) == Decision.ALLOWED


def test_l3_dependent_l4_verdict():
    """L3 rule with ToPorts defers to L4 stage; port context decides."""
    repo = Repository()
    repo.add(Rule(endpoint_selector=es("bar"), ingress=[
        IngressRule(from_endpoints=[es("foo")],
                    to_ports=[PortRule(ports=[
                        PortProtocol(port="80", protocol="TCP")])])]))
    # No port context: label stage undecided -> denied.
    assert repo.allows_ingress(ctx(["foo"], ["bar"])) == Decision.DENIED
    # Correct port: allowed at L4 stage.
    assert repo.allows_ingress(
        ctx(["foo"], ["bar"], [Port(80, "TCP")])) == Decision.ALLOWED
    # Wrong port: denied.
    assert repo.allows_ingress(
        ctx(["foo"], ["bar"], [Port(81, "TCP")])) == Decision.DENIED
    # Wrong peer: denied.
    assert repo.allows_ingress(
        ctx(["baz"], ["bar"], [Port(80, "TCP")])) == Decision.DENIED


def test_l4_any_proto_expands_tcp_udp():
    repo = Repository()
    repo.add(Rule(endpoint_selector=es("bar"), ingress=[
        IngressRule(to_ports=[PortRule(ports=[
            PortProtocol(port="53", protocol="ANY")])])]))
    l4 = repo.resolve_l4_ingress_policy(ctx([], ["bar"]))
    assert set(l4.keys()) == {"53/TCP", "53/UDP"}


def test_l4_port_context_any_checks_both():
    repo = Repository()
    repo.add(Rule(endpoint_selector=es("bar"), ingress=[
        IngressRule(to_ports=[PortRule(ports=[
            PortProtocol(port="8080", protocol="UDP")])])]))
    assert repo.allows_ingress(
        ctx(["foo"], ["bar"], [Port(8080, "ANY")])) == Decision.ALLOWED
    assert repo.allows_ingress(
        ctx(["foo"], ["bar"], [Port(8080, "TCP")])) == Decision.DENIED


def test_l4_from_requires_folded_into_l4_stage():
    """Reference: repository_test.go:685 TestL3DependentL4IngressFromRequires:
    FromRequires of any rule selecting the target is enforced at L4."""
    repo = Repository()
    repo.add(Rule(endpoint_selector=es("bar"), ingress=[
        IngressRule(from_endpoints=[es("foo")],
                    to_ports=[PortRule(ports=[
                        PortProtocol(port="80", protocol="TCP")])]),
        IngressRule(from_requires=[es("trusted")]),
    ]))
    assert repo.allows_ingress(
        ctx(["foo", "trusted"], ["bar"], [Port(80, "TCP")])) == Decision.ALLOWED
    assert repo.allows_ingress(
        ctx(["foo"], ["bar"], [Port(80, "TCP")])) == Decision.DENIED


def test_wildcard_from_endpoints_allows_all():
    repo = Repository()
    repo.add(Rule(endpoint_selector=es("bar"), ingress=[
        IngressRule(from_endpoints=[EndpointSelector()])]))
    assert repo.allows_ingress(ctx(["anything"], ["bar"])) == Decision.ALLOWED


def test_ingress_rule_no_from_block_does_not_allow():
    """An IngressRule with only ToPorts has empty source selectors; with no
    L3 allow it still resolves at L4 as allow-all-at-L3 for that port."""
    repo = Repository()
    repo.add(Rule(endpoint_selector=es("bar"), ingress=[
        IngressRule(to_ports=[PortRule(ports=[
            PortProtocol(port="80", protocol="TCP")])])]))
    l4 = repo.resolve_l4_ingress_policy(ctx([], ["bar"]))
    assert l4["80/TCP"].allows_all_at_l3()
    assert repo.allows_ingress(
        ctx(["whoever"], ["bar"], [Port(80, "TCP")])) == Decision.ALLOWED


def test_l4_merge_same_port_appends_endpoints():
    repo = Repository()
    repo.add(Rule(endpoint_selector=es("bar"), ingress=[
        IngressRule(from_endpoints=[es("foo")],
                    to_ports=[PortRule(ports=[
                        PortProtocol(port="80", protocol="TCP")])])]))
    repo.add(Rule(endpoint_selector=es("bar"), ingress=[
        IngressRule(from_endpoints=[es("baz")],
                    to_ports=[PortRule(ports=[
                        PortProtocol(port="80", protocol="TCP")])])]))
    l4 = repo.resolve_l4_ingress_policy(ctx([], ["bar"]))
    flt = l4["80/TCP"]
    assert not flt.allows_all_at_l3()
    assert any(s.matches(LabelArray.parse_select("foo")) for s in flt.endpoints)
    assert any(s.matches(LabelArray.parse_select("baz")) for s in flt.endpoints)


def test_l7_parser_conflict_raises():
    """HTTP and Kafka on the same port/proto must conflict
    (reference: rule.go:56-61 mergeL4Port parser mismatch)."""
    repo = Repository()
    repo.add(Rule(endpoint_selector=es("bar"), ingress=[
        IngressRule(to_ports=[PortRule(
            ports=[PortProtocol(port="80", protocol="TCP")],
            rules=L7Rules(http=[PortRuleHTTP(method="GET", path="/")]))])]))
    repo.add(Rule(endpoint_selector=es("bar"), ingress=[
        IngressRule(to_ports=[PortRule(
            ports=[PortProtocol(port="80", protocol="TCP")],
            rules=L7Rules(kafka=[PortRuleKafka(topic="t")]))])]))
    with pytest.raises(PolicyError):
        repo.resolve_l4_ingress_policy(ctx([], ["bar"]))


def test_l7_rules_merge_dedup():
    repo = Repository()
    http = PortRuleHTTP(method="GET", path="/public")
    for _ in range(2):
        repo.add(Rule(endpoint_selector=es("bar"), ingress=[
            IngressRule(to_ports=[PortRule(
                ports=[PortProtocol(port="80", protocol="TCP")],
                rules=L7Rules(http=[http]))])]))
    l4 = repo.resolve_l4_ingress_policy(ctx([], ["bar"]))
    flt = l4["80/TCP"]
    assert flt.l7_parser == "http"
    (rules,) = flt.l7_rules_per_ep.values()
    assert rules.http == [http]


def test_wildcard_l3_l4_rule_wildcards_l7():
    """An L3-only allow overlapping an L7 filter forces L7 allow-all for
    those peers (reference: repository.go:128-170 + TestWildcardL3RulesIngress)."""
    repo = Repository()
    repo.add(Rule(endpoint_selector=es("bar"), ingress=[
        IngressRule(from_endpoints=[es("l3peer")])]))
    repo.add(Rule(endpoint_selector=es("bar"), ingress=[
        IngressRule(from_endpoints=[es("l7peer")],
                    to_ports=[PortRule(
                        ports=[PortProtocol(port="80", protocol="TCP")],
                        rules=L7Rules(http=[PortRuleHTTP(path="/private")]))])]))
    l4 = repo.resolve_l4_ingress_policy(ctx([], ["bar"]))
    flt = l4["80/TCP"]
    l3sel = [s for s in flt.l7_rules_per_ep
             if s.matches(LabelArray.parse_select("l3peer"))]
    assert l3sel, "L3-only peer must appear in L7 rules map"
    # wildcarded: HTTP allow-all rule
    assert flt.l7_rules_per_ep[l3sel[0]].http == [PortRuleHTTP()]


def test_egress_l4_resolution():
    repo = Repository()
    repo.add(Rule(endpoint_selector=es("foo"), egress=[
        EgressRule(to_endpoints=[es("bar")],
                   to_ports=[PortRule(ports=[
                       PortProtocol(port="443", protocol="TCP")])])]))
    l4 = repo.resolve_l4_egress_policy(ctx(["foo"], []))
    assert "443/TCP" in l4
    assert not l4["443/TCP"].ingress


def test_cidr_policy_resolution():
    repo = Repository()
    repo.add(Rule(endpoint_selector=es("foo"), egress=[
        EgressRule(to_cidr=["10.0.0.0/8", "192.168.1.0/24"])]))
    repo.add(Rule(endpoint_selector=es("foo"), egress=[
        EgressRule(to_cidr_set=[CIDRRule(cidr="172.16.0.0/12",
                                         except_cidrs=("172.16.5.0/24",))])]))
    cidr = repo.resolve_cidr_policy(ctx([], ["foo"]))
    assert cidr.egress.covers("10.1.2.3")
    assert cidr.egress.covers("192.168.1.77")
    assert cidr.egress.covers("172.16.4.1")
    assert not cidr.egress.covers("172.16.5.1")  # excepted
    assert not cidr.egress.covers("8.8.8.8")
    s4, _ = cidr.to_bpf_data()
    assert s4 == sorted(s4, reverse=True)
    assert 8 in s4 and 24 in s4


def test_cidr_ingress_l3_only_counted():
    repo = Repository()
    repo.add(Rule(endpoint_selector=es("bar"), ingress=[
        IngressRule(from_cidr=["10.0.0.0/8"])]))
    cidr = repo.resolve_cidr_policy(ctx([], ["bar"]))
    assert cidr.ingress.covers("10.9.9.9")


def test_policy_trace_output():
    repo = Repository()
    _load_can_reach_rules(repo)
    c = traced_context(LabelArray.parse_select("foo"),
                       LabelArray.parse_select("bar"))
    verdict = repo.allows_ingress(c)
    out = c.trace_output()
    assert verdict == Decision.ALLOWED
    assert "Found all required labels" in out
    assert "selected" in out
    assert "Label verdict: allowed" in out


def test_revision_in_l4_policy():
    repo = Repository()
    repo.add(Rule(endpoint_selector=es("bar"), ingress=[
        IngressRule(from_endpoints=[es("foo")])]))
    pol = repo.resolve_l4_policy(ctx(["foo"], ["bar"]))
    assert pol.revision == repo.revision
