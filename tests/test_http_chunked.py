"""Chunked transfer-encoding in the HTTP proxy (round-5 VERDICT #4).

The reference's L7 HTTP path sits on Envoy's full codec, which frames
chunked bodies before cilium_l7policy.cc:127 ever sees a request.
Rounds 1-4 failed the connection closed on ANY chunked request; this
matrix pins the new behavior: legal chunked bodies are strictly framed
and re-serialized, while every ambiguous form still fails closed.
"""

import socket
import socketserver
import threading
import time

import pytest

from cilium_tpu.l7.http import HTTPPolicyEngine
from cilium_tpu.l7.socket_proxy import ListenerContext, SocketProxy
from cilium_tpu.policy.api import PortRuleHTTP
from cilium_tpu.proxy import AccessLog


class _Upstream(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, handler_fn=lambda data: None):
        self.received = []
        self.handler_fn = handler_fn
        super().__init__(("127.0.0.1", 0), _UpHandler)
        threading.Thread(target=self.serve_forever, daemon=True).start()

    @property
    def port(self):
        return self.server_address[1]


class _UpHandler(socketserver.BaseRequestHandler):
    def handle(self):
        while True:
            try:
                data = self.request.recv(65536)
            except OSError:
                return
            if not data:
                return
            self.server.received.append(data)
            reply = self.server.handler_fn(data)
            if reply:
                self.request.sendall(reply)


def _connect(port):
    s = socket.create_connection(("127.0.0.1", port), timeout=5)
    s.settimeout(5)
    return s


def _drain(sock, timeout=2):
    deadline = time.time() + timeout
    sock.settimeout(0.2)
    buf = b""
    while time.time() < deadline:
        try:
            chunk = sock.recv(65536)
        except socket.timeout:
            continue
        except OSError:
            break
        if not chunk:
            break
        buf += chunk
    return buf


@pytest.fixture()
def proxy():
    sp = SocketProxy(access_log=AccessLog())
    yield sp
    sp.shutdown()


def _ctx(upstream, paths="/public/.*"):
    engine = HTTPPolicyEngine([PortRuleHTTP(path=paths)])
    return ListenerContext(
        redirect_id="r:ingress:TCP:80", parser_type="http",
        orig_dst=lambda peer: ("127.0.0.1", upstream.port),
        http_engine_for=lambda peer: engine)


def _wait_upstream(upstream, needle, timeout=3):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if needle in b"".join(upstream.received):
            return True
        time.sleep(0.02)
    return False


HEAD_CHUNKED = (b"POST /public/a HTTP/1.1\r\nHost: h\r\n"
                b"Transfer-Encoding: chunked\r\n\r\n")


def test_valid_chunked_request_forwarded(proxy):
    ok = b"HTTP/1.1 200 OK\r\ncontent-length: 2\r\n\r\nok"
    upstream = _Upstream(lambda data: ok if b"0\r\n\r\n" in data else None)
    port = proxy.start_listener(0, _ctx(upstream))
    c = _connect(port)
    try:
        c.sendall(HEAD_CHUNKED +
                  b"5\r\nhello\r\n6\r\n world\r\n0\r\n\r\n")
        got = _drain(c)
    finally:
        c.close()
        upstream.shutdown()
    blob = b"".join(upstream.received)
    assert b"POST /public/a" in blob
    # body arrives re-framed with the same content
    assert b"5\r\nhello\r\n" in blob and b"6\r\n world\r\n" in blob
    assert blob.endswith(b"0\r\n\r\n")
    assert b"200 OK" in got


def test_chunked_split_across_packets(proxy):
    """Chunk size line, data, and terminator arriving byte-dribbled."""
    upstream = _Upstream()
    port = proxy.start_listener(0, _ctx(upstream))
    c = _connect(port)
    try:
        wire = HEAD_CHUNKED + b"b\r\nhello world\r\n0\r\n\r\n"
        for i in range(0, len(wire), 7):
            c.sendall(wire[i:i + 7])
            time.sleep(0.005)
        assert _wait_upstream(upstream, b"0\r\n\r\n")
    finally:
        c.close()
        upstream.shutdown()
    assert b"b\r\nhello world\r\n" in b"".join(upstream.received)


def test_te_cl_conflict_fails_closed(proxy):
    """TE.CL split-brain: an upstream honoring CL=4 would treat the
    smuggled request as a new pipelined one.  Must reset, never pick."""
    upstream = _Upstream()
    port = proxy.start_listener(0, _ctx(upstream))
    c = _connect(port)
    try:
        c.sendall(b"POST /public/a HTTP/1.1\r\nHost: h\r\n"
                  b"Content-Length: 4\r\n"
                  b"Transfer-Encoding: chunked\r\n\r\n"
                  b"0\r\n\r\nGET /secret HTTP/1.1\r\n\r\n")
        _drain(c)
    finally:
        c.close()
        upstream.shutdown()
    assert not upstream.received


def test_stacked_transfer_encoding_fails_closed(proxy):
    """"gzip, chunked" and obfuscated values are parser-dependent."""
    upstream = _Upstream()
    port = proxy.start_listener(0, _ctx(upstream))
    for te in (b"gzip, chunked", b"xchunked", b"chunked, identity",
               b"chu\tnked"):
        c = _connect(port)
        try:
            c.sendall(b"POST /public/a HTTP/1.1\r\nHost: h\r\n"
                      b"Transfer-Encoding: " + te + b"\r\n\r\n"
                      b"0\r\n\r\n")
            _drain(c, timeout=0.8)
        finally:
            c.close()
    upstream.shutdown()
    assert not upstream.received


def test_obs_fold_header_fails_closed(proxy):
    """A folded continuation line ("\\tgzip") that this parser ignored
    but raw_head carried verbatim would let an upstream honoring
    obs-fold read 'Transfer-Encoding: chunked gzip' — framing desync.
    Any folded or colon-less head line must reset."""
    upstream = _Upstream()
    port = proxy.start_listener(0, _ctx(upstream))
    for head in (b"POST /public/a HTTP/1.1\r\nHost: h\r\n"
                 b"Transfer-Encoding: chunked\r\n\tgzip\r\n\r\n",
                 b"POST /public/a HTTP/1.1\r\nHost: h\r\n"
                 b"Content-Length: 5\r\n colon-less junk\r\n\r\n"):
        c = _connect(port)
        try:
            c.sendall(head + b"0\r\n\r\nGET /secret HTTP/1.1\r\n\r\n")
            _drain(c, timeout=0.8)
        finally:
            c.close()
    upstream.shutdown()
    assert not upstream.received


def test_malformed_chunk_size_fails_closed(proxy):
    """Signs, whitespace, extensions, and overlong sizes in the
    chunk-size line all reset; the pipelined follow-up never leaks."""
    for bad in (b"+5", b"5;ext=1", b" 5", b"5 ", b"0x5", b"",
                b"ffffffffffffffffff", b"5\n"):
        upstream = _Upstream()
        port = proxy.start_listener(0, _ctx(upstream))
        c = _connect(port)
        try:
            c.sendall(HEAD_CHUNKED + bad + b"\r\nhello\r\n0\r\n\r\n"
                      b"GET /secret HTTP/1.1\r\n\r\n")
            _drain(c, timeout=0.8)
        finally:
            c.close()
            proxy.stop_listener("r:ingress:TCP:80")
            upstream.shutdown()
        blob = b"".join(upstream.received)
        assert b"secret" not in blob, bad


def test_chunk_data_missing_crlf_fails_closed(proxy):
    """Chunk data must be followed by exactly CRLF; a bare LF (or
    overlong data) is the disagreement smuggling rides on."""
    upstream = _Upstream()
    port = proxy.start_listener(0, _ctx(upstream))
    c = _connect(port)
    try:
        c.sendall(HEAD_CHUNKED + b"5\r\nhelloXX"
                  b"GET /secret HTTP/1.1\r\n\r\n")
        _drain(c, timeout=0.8)
    finally:
        c.close()
        upstream.shutdown()
    assert b"secret" not in b"".join(upstream.received)


def test_valid_trailers_strictly_parsed_and_discarded(proxy):
    """Legal trailers don't kill the exchange but are not forwarded:
    fields arriving after the policy check can't reach upstream."""
    upstream = _Upstream()
    port = proxy.start_listener(0, _ctx(upstream))
    c = _connect(port)
    try:
        c.sendall(HEAD_CHUNKED + b"2\r\nhi\r\n0\r\n"
                  b"X-Checksum: abc123\r\n\r\n")
        assert _wait_upstream(upstream, b"0\r\n\r\n")
    finally:
        c.close()
        upstream.shutdown()
    blob = b"".join(upstream.received)
    assert b"2\r\nhi\r\n" in blob
    assert b"X-Checksum" not in blob


def test_framing_header_in_trailers_fails_closed(proxy):
    upstream = _Upstream()
    port = proxy.start_listener(0, _ctx(upstream))
    c = _connect(port)
    try:
        c.sendall(HEAD_CHUNKED + b"2\r\nhi\r\n0\r\n"
                  b"Content-Length: 99\r\n\r\n"
                  b"GET /secret HTTP/1.1\r\n\r\n")
        _drain(c, timeout=0.8)
    finally:
        c.close()
        upstream.shutdown()
    assert b"secret" not in b"".join(upstream.received)


def test_malformed_trailer_line_fails_closed(proxy):
    upstream = _Upstream()
    port = proxy.start_listener(0, _ctx(upstream))
    for trailer in (b"no-colon-here", b": empty-name", b"sp ace: v"):
        c = _connect(port)
        try:
            c.sendall(HEAD_CHUNKED + b"2\r\nhi\r\n0\r\n"
                      + trailer + b"\r\n\r\n"
                      b"GET /secret HTTP/1.1\r\n\r\n")
            _drain(c, timeout=0.8)
        finally:
            c.close()
    upstream.shutdown()
    assert b"secret" not in b"".join(upstream.received)


def test_denied_chunked_request_never_reaches_upstream(proxy):
    """The policy check runs on the head before any body byte is
    forwarded; a denied chunked POST leaves upstream untouched."""
    upstream = _Upstream()
    port = proxy.start_listener(0, _ctx(upstream))
    c = _connect(port)
    try:
        c.sendall(b"POST /secret HTTP/1.1\r\nHost: h\r\n"
                  b"Transfer-Encoding: chunked\r\n\r\n"
                  b"5\r\nhello\r\n0\r\n\r\n")
        got = _drain(c)
    finally:
        c.close()
        upstream.shutdown()
    assert b"403" in got
    assert not upstream.received


def test_pipelined_request_after_chunked_body_is_policy_checked(proxy):
    """Bytes after a valid chunked body are the NEXT request, not body
    spill: a denied pipelined request must not leak upstream."""
    upstream = _Upstream()
    port = proxy.start_listener(0, _ctx(upstream))
    c = _connect(port)
    try:
        c.sendall(HEAD_CHUNKED + b"5\r\nhello\r\n0\r\n\r\n"
                  b"GET /secret HTTP/1.1\r\nHost: h\r\n\r\n")
        got = _drain(c)
    finally:
        c.close()
        upstream.shutdown()
    blob = b"".join(upstream.received)
    assert b"POST /public/a" in blob
    assert b"secret" not in blob
    assert b"403" in got
