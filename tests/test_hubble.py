"""Hubble flow observability: filter grammar, device-aggregation
oracle parity, flow store cursors, relay degradation, and Prometheus
conformance of the flow-derived metrics."""

import threading
import time

import numpy as np
import pytest

from cilium_tpu.hubble.aggregation import (FlowTable, aggregate_oracle,
                                           flow_update_step,
                                           make_flow_state,
                                           snapshot_to_oracle_form)
from cilium_tpu.hubble.filter import (FlowFilter, parse_drop_reason,
                                      parse_proto, parse_tier,
                                      parse_verdict)
from cilium_tpu.hubble.flow import (FlowRecord, FlowStore,
                                    flow_from_access_log,
                                    flow_from_event, verdict_of_event)
from cilium_tpu.hubble.observer import FlowObserver
from cilium_tpu.hubble.relay import HubbleRelay
from cilium_tpu.monitor import MonitorEvent, MonitorHub


def _flow(seq=1, **kw):
    base = dict(seq=seq, timestamp=100.0, node="n1",
                verdict="FORWARDED", src_identity=256,
                dst_identity=512, endpoint=3, dport=80, proto=6,
                length=100, event=0)
    base.update(kw)
    return FlowRecord(**base)


# ---------------------------------------------------------------- filters

class TestFilterGrammar:
    def test_empty_filter_matches_all(self):
        assert FlowFilter().matches(_flow())

    @pytest.mark.parametrize("field,value,flow_kw", [
        ("src_identity", 256, {}),
        ("dst_identity", 512, {}),
        ("endpoint", 3, {}),
        ("dport", 80, {}),
        ("proto", 6, {}),
        ("verdict", "FORWARDED", {}),
        ("drop_reason", "Policy denied (L3/L4)",
         {"verdict": "DROPPED", "drop_reason": "Policy denied (L3/L4)"}),
        ("tier", "deny",
         {"verdict": "DROPPED", "tier": "deny",
          "matched_rule": "deny:identity=256,dport=80,proto=6"}),
        ("l7_protocol", "http", {"l7_protocol": "http"}),
        ("l7_method", "GET", {"l7_protocol": "http",
                              "l7_method": "GET"}),
        ("l7_status", 403, {"l7_protocol": "http", "l7_status": 403}),
        ("node", "n1", {}),
    ])
    def test_each_predicate_match_and_reject(self, field, value,
                                             flow_kw):
        flt = FlowFilter(**{field: value})
        assert flt.matches(_flow(**flow_kw))
        # a flow differing in that one field must not match
        wrong = {"src_identity": 1, "dst_identity": 1, "endpoint": 9,
                 "dport": 81, "proto": 17, "verdict": "DROPPED",
                 "drop_reason": "Prefilter denied",
                 "tier": "ct-established",
                 "l7_protocol": "dns", "l7_method": "PUT",
                 "l7_status": 200, "node": "other"}
        assert not flt.matches(_flow(**{**flow_kw,
                                        field: wrong[field]}))

    def test_identity_matches_either_side(self):
        flt = FlowFilter(identity=512)
        assert flt.matches(_flow(src_identity=512, dst_identity=9))
        assert flt.matches(_flow(src_identity=9, dst_identity=512))
        assert not flt.matches(_flow(src_identity=9, dst_identity=8))

    def test_l7_path_is_prefix_match(self):
        flt = FlowFilter(l7_path="/api/")
        assert flt.matches(_flow(l7_protocol="http",
                                 l7_path="/api/v1/users"))
        assert not flt.matches(_flow(l7_protocol="http",
                                     l7_path="/public/x"))

    def test_since_cursor_excludes_older(self):
        flt = FlowFilter(since=5)
        assert not flt.matches(_flow(seq=5))
        assert flt.matches(_flow(seq=6))

    def test_conjunction(self):
        flt = FlowFilter(verdict="DROPPED", dport=443, proto=6,
                         src_identity=256)
        hit = _flow(verdict="DROPPED", dport=443)
        assert flt.matches(hit)
        assert not flt.matches(_flow(verdict="DROPPED", dport=80))
        assert not flt.matches(_flow(verdict="FORWARDED", dport=443))

    def test_from_query_round_trip(self):
        flt = FlowFilter.from_query({
            "verdict": ["dropped"], "proto": ["tcp"],
            "identity": ["256"], "dport": ["443"],
            "drop_reason": ["-133"], "l7_path": ["/x"]})
        assert flt.verdict == "DROPPED"
        assert flt.proto == 6
        assert flt.identity == 256
        assert flt.dport == 443
        assert flt.drop_reason == "Prefilter denied"
        back = FlowFilter.from_query(flt.to_query())
        assert back == flt

    def test_to_query_strips_cursor_and_node(self):
        q = FlowFilter(since=9, node="n1", dport=80).to_query()
        assert "since" not in q and "node" not in q
        assert q["dport"] == "80"

    def test_tier_filter_forms_and_round_trip(self):
        # name (case-insensitive) and numeric code both parse
        from cilium_tpu.datapath.events import TIER_DENY
        assert parse_tier("DENY") == "deny"
        assert parse_tier(TIER_DENY) == "deny"
        assert parse_tier("l7-redirect") == "l7-redirect"
        with pytest.raises(ValueError):
            parse_tier("nope")
        with pytest.raises(ValueError):
            parse_tier(99)
        flt = FlowFilter.from_query({"tier": ["L3-ALLOW"]})
        assert flt.tier == "l3-allow"
        assert flt.matches(_flow(tier="l3-allow"))
        assert not flt.matches(_flow(tier="l4-rule"))
        assert not flt.matches(_flow())  # no provenance -> no match
        back = FlowFilter.from_query(flt.to_query())
        assert back == flt

    def test_drop_reason_with_tier_conjunction(self):
        flt = FlowFilter.from_query({
            "drop_reason": ["policy denied (l3/l4)"],
            "tier": ["deny"], "verdict": ["DROPPED"]})
        hit = _flow(verdict="DROPPED",
                    drop_reason="Policy denied (L3/L4)", tier="deny")
        assert flt.matches(hit)
        assert not flt.matches(_flow(
            verdict="DROPPED", drop_reason="Policy denied (L3/L4)",
            tier="ct-established"))

    def test_parse_helpers_and_errors(self):
        assert parse_proto("UDP") == 17
        assert parse_proto(58) == 58
        assert parse_verdict("redirected") == "REDIRECTED"
        assert parse_drop_reason("prefilter denied") == \
            "Prefilter denied"
        with pytest.raises(ValueError):
            parse_verdict("nope")
        with pytest.raises(ValueError):
            parse_drop_reason("no such reason")
        with pytest.raises(ValueError):
            parse_drop_reason("-1")

    def test_verdict_of_event(self):
        from cilium_tpu.datapath.events import (DROP_POLICY,
                                                TRACE_TO_LXC,
                                                TRACE_TO_PROXY)
        assert verdict_of_event(DROP_POLICY) == "DROPPED"
        assert verdict_of_event(TRACE_TO_PROXY) == "REDIRECTED"
        assert verdict_of_event(TRACE_TO_LXC) == "FORWARDED"


# ------------------------------------------------- device-oracle parity

class TestAggregationOracle:
    def _random_batches(self, seed, batches=4, b=512):
        rng = np.random.default_rng(seed)
        for it in range(batches):
            yield (rng.integers(256, 280, b),
                   rng.integers(256, 280, b),
                   rng.integers(1, 5, b) * 1000,
                   np.where(rng.random(b) < 0.5, 6, 17),
                   rng.choice([-130, -133, -134, 0, 1, 4], b),
                   rng.integers(40, 1500, b),
                   100 + it)

    @pytest.mark.parametrize("seed", [0, 7, 23])
    def test_counters_bit_exact_vs_numpy_oracle(self, seed):
        # ls_stripe=1: last-seen exact per batch (the parity config);
        # counters are exact at every stripe
        ft = FlowTable(slots=1 << 14, max_probe=16, ls_stripe=1)
        oracle = {}
        for (src, dst, dport, proto, event, length, now) in \
                self._random_batches(seed):
            ft.update(src, dst, dport, proto, event, length, now)
            o = aggregate_oracle(src, dst, dport, proto, event,
                                 length, now)
            for k, (p, by, ls) in o.items():
                p0, b0, l0 = oracle.get(k, (0, 0, 0))
                oracle[k] = ((p0 + p) & 0xFFFFFFFF,
                             (b0 + by) & 0xFFFFFFFF, max(l0, ls))
        assert ft.lost == 0
        dev = snapshot_to_oracle_form(ft.snapshot())
        assert dev == oracle  # bit-exact: packets, bytes, last_seen

    def test_uint32_byte_counter_wrap_matches_oracle(self):
        ft = FlowTable(slots=1 << 6, max_probe=8, ls_stripe=1)
        src = np.full(8, 256)
        dst = np.full(8, 512)
        dport = np.full(8, 80)
        proto = np.full(8, 6)
        event = np.zeros(8, np.int32)
        length = np.full(8, 0x7FFFFFF0)
        for now in (1, 2):
            ft.update(src, dst, dport, proto, event, length, now)
        oracle = {}
        for now in (1, 2):
            o = aggregate_oracle(src, dst, dport, proto, event,
                                 length, now)
            for k, (p, by, ls) in o.items():
                p0, b0, l0 = oracle.get(k, (0, 0, 0))
                oracle[k] = (p0 + p, (b0 + by) & 0xFFFFFFFF,
                             max(l0, ls))
        assert snapshot_to_oracle_form(ft.snapshot()) == oracle

    def test_table_exhaustion_counts_lost_not_corrupt(self):
        # 16 slots, hundreds of distinct keys: most rows are lost, and
        # the tracked flows' counters stay exact
        ft = FlowTable(slots=16, max_probe=4, ls_stripe=1)
        rng = np.random.default_rng(1)
        b = 512
        src = rng.integers(0, 1 << 20, b)
        ft.update(src, src, np.full(b, 80), np.full(b, 6),
                  np.zeros(b, np.int64), np.full(b, 100), now=5)
        assert ft.lost > 0
        snap = ft.snapshot()
        assert 0 < len(snap) <= 16
        tracked = sum(f["packets"] for f in snap)
        assert tracked + ft.lost == b

    def test_claim_budget_throttles_births(self):
        ft = FlowTable(slots=1 << 12, max_probe=8, claim_budget=64,
                       ls_stripe=1)
        rng = np.random.default_rng(2)
        b = 512
        src = rng.integers(0, 1 << 20, b)  # ~all distinct flows
        args = (src, src, np.full(b, 80), np.full(b, 6),
                np.zeros(b, np.int64), np.full(b, 100))
        ft.update(*args, now=1)
        first = ft.entry_count()
        assert first <= 64
        for i in range(12):
            ft.update(*args, now=2 + i)
        assert ft.entry_count() > first  # births continue over batches

    def test_fused_pipeline_matches_monitor_view(self):
        """The in-pipeline aggregation (engine path) keys flows by the
        endpoint's own identity and the resolved peer identity."""
        from cilium_tpu.datapath.engine import Datapath, make_full_batch
        from cilium_tpu.policy.mapstate import (EGRESS, PolicyKey,
                                                PolicyMapState,
                                                PolicyMapStateEntry)
        st = PolicyMapState()
        st[PolicyKey(identity=256, dest_port=80, nexthdr=6,
                     direction=EGRESS)] = PolicyMapStateEntry()
        dp = Datapath(ct_slots=1 << 10)
        dp.enable_flow_aggregation(slots=1 << 10, claim_every=1)
        dp.load_policy([st], revision=1,
                       ipcache_prefixes={"10.0.0.0/24": 256})
        dp.set_endpoint_identity(0, 999)
        pkt = make_full_batch(
            endpoint=[0, 0, 0, 0],
            saddr=["10.1.1.1"] * 4,
            daddr=["10.0.0.5", "10.0.0.5", "10.0.0.9", "10.0.0.5"],
            sport=[1111, 1112, 1113, 1111],
            dport=[80, 80, 443, 80], length=[100, 200, 300, 400])
        dp.process(pkt, now=50)
        snap = {(f["src-identity"], f["dst-identity"], f["dport"],
                 f["event"]): (f["packets"], f["bytes"], f["last-seen"])
                for f in dp.flow_snapshot()}
        from cilium_tpu.datapath.events import DROP_POLICY
        assert snap[(999, 256, 80, 0)] == (3, 700, 50)
        assert snap[(999, 256, 443, DROP_POLICY)] == (1, 300, 50)
        # v6 shares the identity-keyed table
        from cilium_tpu.datapath.engine import make_full_batch6
        b6 = make_full_batch6(endpoint=[0], saddr=["fd00::1"],
                              daddr=["fd00::2"], sport=[1], dport=[53],
                              proto=[17], length=[80])
        dp.process6(b6, now=51)
        snap2 = dp.flow_snapshot()
        assert any(f["proto"] == 17 and f["dport"] == 53
                   for f in snap2)
        stats = dp.flow_stats()
        assert stats["occupied"] == len(snap2)
        assert stats["claim-every"] == 1

    def test_sharded_update_matches_oracle(self):
        """Replicated table + batch-sharded inputs on the 8-device
        virtual mesh produce the same aggregates."""
        import functools

        import jax
        from cilium_tpu.hubble.aggregation import place_sharded
        from cilium_tpu.parallel.mesh import (batch_sharding, make_mesh,
                                              replicate)
        mesh = make_mesh()
        rng = np.random.default_rng(5)
        b = 1024
        src = rng.integers(256, 270, b).astype(np.int32)
        dst = rng.integers(256, 270, b).astype(np.int32)
        dport = rng.integers(1, 4, b).astype(np.int32) * 100
        proto = np.full(b, 6, np.int32)
        event = np.zeros(b, np.int32)
        length = np.full(b, 64, np.int32)
        slots = 1 << 12
        state = place_sharded(make_flow_state(slots), mesh)
        import jax.numpy as jnp
        sh = batch_sharding(mesh)
        args = [jax.device_put(jnp.asarray(a), sh)
                for a in (src, dst, dport, proto, event, length)]
        step = jax.jit(functools.partial(
            flow_update_step, slots=slots, max_probe=8, ls_stripe=1))
        state = step(state, *args, jnp.int32(7))
        ft = FlowTable(slots=slots, max_probe=8, ls_stripe=1)
        ft.state = state
        dev = snapshot_to_oracle_form(ft.snapshot())
        assert dev == aggregate_oracle(src, dst, dport, proto, event,
                                       length, 7)


# ------------------------------------------------------------ flow store

class TestFlowStore:
    def test_monotonic_seq_and_since(self):
        store = FlowStore(capacity=100)
        for i in range(10):
            store.add(_flow(seq=0, dport=i))
        assert store.last_seq == 10
        assert [f.seq for f in store.get(limit=0)] == \
            list(range(1, 11))
        tail = store.get(since=7, limit=0)
        assert [f.seq for f in tail] == [8, 9, 10]

    def test_eviction_accounted(self):
        store = FlowStore(capacity=5)
        for i in range(12):
            store.add(_flow())
        assert store.stats()["ringed"] == 5
        assert store.evicted == 7
        assert [f.seq for f in store.get(limit=0)] == \
            list(range(8, 13))

    def test_filtered_get_with_limit(self):
        store = FlowStore(capacity=100)
        for i in range(20):
            store.add(_flow(verdict="DROPPED" if i % 2 else
                            "FORWARDED"))
        drops = store.get(FlowFilter(verdict="DROPPED"), limit=3)
        assert len(drops) == 3
        assert all(f.verdict == "DROPPED" for f in drops)
        # newest matches win when more qualify
        assert drops[-1].seq == 20


# ----------------------------------------------------- observer ingestion

class TestObserver:
    def test_monitor_and_access_log_become_flows(self):
        hub = MonitorHub()
        obs = FlowObserver(node="nX")
        obs.attach_monitor(hub)
        hub.ingest_batch(np.array([-130, 0]), np.array([1, 2]),
                         np.array([256, 257]), np.array([80, 81]),
                         np.array([6, 6]), np.array([100, 200]))
        flows = obs.get_flows(limit=10)
        assert len(flows) == 2
        drop = [f for f in flows if f["verdict"] == "DROPPED"][0]
        assert drop["drop_reason"] == "Policy denied (L3/L4)"
        assert drop["node"] == "nX"

        from cilium_tpu.proxy import AccessLogEntry
        obs._on_access_log(AccessLogEntry(
            timestamp=time.time(), proxy_id="1:ingress:TCP:80",
            l7_protocol="http", verdict="denied", src_identity=9,
            dst_identity=10,
            info={"method": "GET", "path": "/admin", "status": 403}))
        l7 = obs.get_flows(FlowFilter(l7_protocol="http"), limit=10)
        assert len(l7) == 1
        assert l7[0]["verdict"] == "DROPPED"
        assert l7[0]["l7_method"] == "GET"
        assert l7[0]["l7_status"] == 403

    def test_agent_and_l7_monitor_notes_are_skipped(self):
        hub = MonitorHub()
        obs = FlowObserver(node="nX")
        obs.attach_monitor(hub)
        hub.notify_agent("policy-updated", "revision=1")
        assert obs.get_flows(limit=10) == []

    def test_provenance_rides_monitor_events_into_flows(self):
        """Events ingested with tiers/match_slots become flow records
        filterable by decision tier, rendered with tier + rule."""
        from cilium_tpu.datapath.events import (TIER_DENY, TIER_L4_RULE,
                                                format_denied_key)
        hub = MonitorHub()
        obs = FlowObserver(node="nX")
        obs.attach_monitor(hub)
        hub.ingest_batch(np.array([-130, 0]), np.array([1, 2]),
                         np.array([256, 257]), np.array([80, 81]),
                         np.array([6, 6]), np.array([100, 200]),
                         tiers=np.array([TIER_DENY, TIER_L4_RULE]),
                         match_slots=np.array([-1, 5]),
                         rule_of=lambda s: "identity=257,dport=81,"
                                           "proto=6,egress")
        denied = obs.get_flows(FlowFilter(tier="deny"), limit=10)
        assert len(denied) == 1
        assert denied[0]["matched_rule"] == \
            format_denied_key(256, 80, 6)
        allowed = obs.get_flows(FlowFilter(tier="l4-rule"), limit=10)
        assert len(allowed) == 1
        assert allowed[0]["matched_rule"].startswith("identity=257")
        from cilium_tpu.hubble.flow import flow_from_dict
        text = flow_from_dict(denied[0]).describe()
        assert "tier=deny" in text and "rule=deny:" in text
        # cumulative per-rule drop accounting rides along
        assert hub.top_dropped_rules()[0]["rule"] == \
            format_denied_key(256, 80, 6)


# ------------------------------------------------------ relay degradation

class _LocalPeer:
    """In-process peer: a FlowStore behind the fetch contract."""

    def __init__(self, node):
        self.store = FlowStore()
        self.node = node

    def fetch(self, query, since, limit):
        flt = FlowFilter.from_query(query)
        return {"flows": [f.to_dict() for f in
                          self.store.get(flt, since=since,
                                         limit=limit)]}


class TestRelay:
    def _relay_with_two_peers(self):
        a, b = _LocalPeer("a"), _LocalPeer("b")
        for i in range(3):
            a.store.add(_flow(node="a", dport=80))
            b.store.add(_flow(node="b", dport=443,
                              verdict="DROPPED"))
        relay = HubbleRelay(deadline_s=0.5)
        relay.add_peer("a", a.fetch)
        relay.add_peer("b", b.fetch)
        return relay, a, b

    def test_federated_merge(self):
        relay, _a, _b = self._relay_with_two_peers()
        out = relay.get_flows(limit=10)
        assert not out["partial"]
        assert len(out["flows"]) == 6
        assert {n["name"] for n in out["nodes"]} == {"a", "b"}
        assert all(n["status"] == "ok" for n in out["nodes"])
        # filters fan out to peers
        drops = relay.get_flows(FlowFilter(verdict="DROPPED"),
                                limit=10)
        assert len(drops["flows"]) == 3
        assert all(f["node"] == "b" for f in drops["flows"])

    def test_dead_peer_degrades_to_flagged_partial(self):
        relay, _a, _b = self._relay_with_two_peers()

        def dead(query, since, limit):
            raise ConnectionRefusedError("peer down")

        relay.add_peer("dead", dead)
        out = relay.get_flows(limit=10)
        assert out["partial"]
        assert len(out["flows"]) == 6  # live peers still answer
        status = {n["name"]: n["status"] for n in out["nodes"]}
        assert status["dead"] == "error"
        assert status["a"] == "ok"

    def test_hung_peer_times_out_not_hangs(self):
        relay, _a, _b = self._relay_with_two_peers()
        release = threading.Event()

        def hung(query, since, limit):
            release.wait(30)
            return {"flows": []}

        relay.add_peer("hung", hung)
        t0 = time.monotonic()
        out = relay.get_flows(limit=10)
        elapsed = time.monotonic() - t0
        release.set()
        assert elapsed < 5.0  # bounded by the 0.5s deadline, not 30s
        assert out["partial"]
        status = {n["name"]: n["status"] for n in out["nodes"]}
        assert status["hung"] == "timeout"
        assert len(out["flows"]) == 6

    def test_breaker_opens_and_recovers(self):
        relay, a, _b = self._relay_with_two_peers()
        state = {"up": False}

        def flaky(query, since, limit):
            if not state["up"]:
                raise ConnectionRefusedError("down")
            return {"flows": [_flow(node="flaky").to_dict()]}

        relay.add_peer("flaky", flaky)
        # threshold=2 failures -> open
        relay.get_flows(limit=5)
        relay.get_flows(limit=5)
        out = relay.get_flows(limit=5)
        status = {n["name"]: n for n in out["nodes"]}
        assert status["flaky"]["status"] == "breaker-open"
        assert status["flaky"]["breaker"] in ("open", "half-open")
        # recovery: wait out the reset timeout, peer comes back
        state["up"] = True
        deadline = time.time() + 5
        while time.time() < deadline:
            out = relay.get_flows(limit=5)
            st = {n["name"]: n["status"] for n in out["nodes"]}
            if st["flaky"] == "ok":
                break
            time.sleep(0.1)
        assert st["flaky"] == "ok"
        assert not out["partial"]
        health = {h["name"]: h for h in relay.node_health()}
        assert health["flaky"]["breaker"] == "closed"


# ----------------------------------------------------- monitor cursor

class TestMonitorCursor:
    def _burst(self, hub, n, code=0):
        hub.ingest_batch(np.full(n, code), np.zeros(n, int),
                         np.zeros(n, int), np.zeros(n, int),
                         np.full(n, 6), np.full(n, 100))

    def test_seq_monotonic_and_since(self):
        hub = MonitorHub(samples_per_batch=8)
        self._burst(hub, 4)
        self._burst(hub, 4)
        events = hub.tail(100)
        seqs = [e.seq for e in events]
        assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
        cursor = seqs[3]
        later = hub.tail(100, since=cursor)
        assert [e.seq for e in later] == seqs[4:]
        assert hub.last_seq == seqs[-1]

    def test_cursor_sees_burst_beyond_page_size(self):
        """Events between polls beyond one page are not silently
        missed: a since-poll pages FORWARD (oldest-first) from the
        cursor, so the follower drains a burst page by page (the
        pre-cursor CLI capped at the newest n and dropped the middle).
        since=0 keeps the legacy newest-n view (the first poll)."""
        hub = MonitorHub(samples_per_batch=16)
        self._burst(hub, 1)
        first = hub.tail(10)          # since unset: newest view
        cursor = first[-1].seq
        got = []
        for _ in range(6):  # 6 bursts x 32 samples, paged 10 at a time
            self._burst(hub, 16)
            self._burst(hub, 16, code=-130)
            while True:
                page = hub.tail(10, since=cursor)
                if not page:
                    break
                got.extend(e.seq for e in page)
                cursor = page[-1].seq
        assert got == list(range(first[-1].seq + 1,
                                 hub.last_seq + 1))

    def test_agent_events_and_wire_dict_carry_seq(self):
        from cilium_tpu.monitor import _monitor_event_dict
        hub = MonitorHub()
        hub.notify_agent("endpoint-created", "id=5")
        ev = hub.tail(1)[0]
        assert ev.seq == 1
        assert _monitor_event_dict(ev)["seq"] == 1


# ---------------------------------------------- prometheus conformance

class TestHubbleMetricsConformance:
    def _fresh_series(self):
        # the process-global registry is shared; craft label sets
        # unique to this test so assertions are stable
        from cilium_tpu.utils.metrics import registry
        return registry

    def test_counter_label_escaping(self):
        from cilium_tpu.utils.metrics import HUBBLE_DROPS, registry
        HUBBLE_DROPS.inc(labels={
            "reason": 'weird "quoted" back\\slash\nnewline',
            "src_identity": "77701", "dst_identity": "77702"})
        text = registry.expose_text()
        line = [l for l in text.splitlines()
                if "77701" in l and "hubble_drop_total" in l]
        assert len(line) == 1
        assert '\\"quoted\\"' in line[0]
        assert "back\\\\slash" in line[0]
        assert "\\n" in line[0] and "\n" not in \
            line[0].replace("\\n", "")

    @staticmethod
    def _relay_hist_lines(text):
        return {l.rsplit(" ", 1)[0]: float(l.rsplit(" ", 1)[1])
                for l in text.splitlines()
                if l.startswith("cilium_tpu_hubble_relay_peer_seconds")}

    def test_histogram_buckets_sum_count(self):
        # delta-based: the registry is process-global, so earlier
        # relay tests may already have observations in this series
        from cilium_tpu.utils.metrics import (HUBBLE_RELAY_SECONDS,
                                              registry)
        before = self._relay_hist_lines(registry.expose_text())
        for v in (0.0002, 0.003, 0.003, 0.2, 7.0):
            HUBBLE_RELAY_SECONDS.observe(v)
        text = registry.expose_text()
        after = self._relay_hist_lines(text)
        buckets = {k: v for k, v in after.items() if "_bucket" in k}
        assert buckets, text
        # cumulative, monotone nondecreasing in bucket order
        ordered = [v for k, v in after.items() if "_bucket" in k]
        assert ordered == sorted(ordered)
        inf_key = [k for k in buckets if 'le="+Inf"' in k]
        assert len(inf_key) == 1
        count_key = [k for k in after if k.endswith("_count")][0]
        sum_key = [k for k in after if k.endswith("_sum")][0]
        # +Inf == _count, both grew by exactly the 5 observations
        assert after[inf_key[0]] == after[count_key]
        assert after[count_key] - before.get(count_key, 0.0) == 5.0
        assert abs((after[sum_key] - before.get(sum_key, 0.0)) -
                   7.2062) < 1e-6
        # the le="0.001" bucket gained only the 0.0002 observation
        small = [k for k in buckets if 'le="0.001"' in k][0]
        assert after[small] - before.get(small, 0.0) == 1.0
        # TYPE declared
        assert "# TYPE cilium_tpu_hubble_relay_peer_seconds histogram" \
            in text

    def test_flow_derived_series(self):
        from cilium_tpu.utils.metrics import (HUBBLE_DNS_RESPONSES,
                                              HUBBLE_DROPS,
                                              HUBBLE_FLOWS_PROCESSED,
                                              HUBBLE_HTTP_RESPONSES)
        obs = FlowObserver(node="metrics-test")
        before = HUBBLE_FLOWS_PROCESSED.total()
        obs.ingest(_flow(verdict="DROPPED",
                         drop_reason="Prefilter denied",
                         src_identity=88801, dst_identity=88802))
        obs.ingest(_flow(l7_protocol="http", l7_method="GET",
                         l7_status=503))
        obs.ingest(_flow(l7_protocol="dns", l7_status=3))
        assert HUBBLE_FLOWS_PROCESSED.total() == before + 3
        assert HUBBLE_DROPS.value(labels={
            "reason": "Prefilter denied", "src_identity": "88801",
            "dst_identity": "88802"}) == 1
        assert HUBBLE_HTTP_RESPONSES.value(labels={
            "status": "503", "method": "GET"}) >= 1
        assert HUBBLE_DNS_RESPONSES.value(labels={"rcode": "3"}) >= 1
