"""IPv6 end to end: 4-word LPM, prefilter v6, v6 datapath, CIDR policy.

Reference parity targets:
  * bpf_lxc.c:114 ipv6_l3_from_lxc / :745 ipv6_policy — the v6 packet
    path with conntrack + policy verdict;
  * bpf_xdp.c check_v6 + pkg/datapath/prefilter (dyn/fixed v6 maps);
  * pkg/maps/ipcache — family-tagged LPM keys (here: a second LPM with
    full 128-bit compares);
  * pkg/policy/l3.go — v6 CIDR policy prefix-length accounting.
"""

import ipaddress
import random

import jax.numpy as jnp
import numpy as np

from cilium_tpu.compiler.lpm import (compile_lpm6, ipv6_batch_words,
                                     oracle_lpm)
from cilium_tpu.datapath.engine import (Datapath, make_full_batch6)
from cilium_tpu.datapath.events import (DROP_POLICY, DROP_PREFILTER,
                                        TRACE_TO_LXC, TRACE_TO_PROXY)
from cilium_tpu.datapath.prefilter import PreFilter, PrefilterType
from cilium_tpu.ops.lpm_ops import lpm6_lookup
from cilium_tpu.policy.mapstate import (EGRESS, INGRESS, PolicyKey,
                                        PolicyMapState, PolicyMapStateEntry)


def _lookup6(t, ips):
    addrs = jnp.asarray(ipv6_batch_words(ips))
    found, val = lpm6_lookup(
        jnp.asarray(t.masks), jnp.asarray(t.k0), jnp.asarray(t.k1),
        jnp.asarray(t.k2), jnp.asarray(t.k3), jnp.asarray(t.kb),
        jnp.asarray(t.value), jnp.asarray(t.prefix_lens), addrs,
        t.max_probe)
    return np.asarray(found), np.asarray(val)


PREFIXES = {
    "2001:db8::/32": 7,
    "::/0": 1,
    "2001:db8:1::/48": 9,
    "fe80::/10": 3,
    "2001:db8:1:2::/64": 11,
    "::1/128": 42,
    "2001:db8:1:2:3:4:5:6/128": 77,
}


def test_lpm6_oracle_parity_fixed_cases():
    t = compile_lpm6(PREFIXES)
    ips = ["2001:db8:1:2::5", "2001:db8:1::9", "2001:db8:ffff::1",
           "fe80::1", "::1", "9999::1", "2001:db8:1:2:3:4:5:6"]
    _found, val = _lookup6(t, ips)
    assert val.tolist() == [oracle_lpm(PREFIXES, ip) for ip in ips]


def test_lpm6_oracle_parity_fuzz():
    rng = random.Random(7)
    t = compile_lpm6(PREFIXES)
    # random addresses plus boundary-biased ones (prefix edges)
    ips = [str(ipaddress.IPv6Address(rng.getrandbits(128)))
           for _ in range(256)]
    for cidr in PREFIXES:
        net = ipaddress.ip_network(cidr)
        ips.append(str(net.network_address))
        ips.append(str(net.broadcast_address))
    _found, val = _lookup6(t, ips)
    want = [oracle_lpm(PREFIXES, ip) for ip in ips]
    assert val.tolist() == want


def test_lpm6_empty_table():
    t = compile_lpm6({})
    found, val = _lookup6(t, ["::1"])
    assert not found[0] and val[0] == -1


# ------------------------------------------------------------ prefilter

def test_prefilter_v6_insert_no_longer_raises():
    pf = PreFilter()
    pf.insert(["2001:db8:bad::/48", "203.0.113.0/24"])
    cidrs, _rev = pf.dump()
    assert "2001:db8:bad::/48" in cidrs and "203.0.113.0/24" in cidrs


def test_prefilter_v6_drop_mask_and_delete():
    pf = PreFilter()
    pf.insert(["2001:db8:bad::/48"], PrefilterType.PREFIX_DYN_V6)
    pf.insert(["fe80::/10"], PrefilterType.PREFIX_FIX_V6)
    addrs = jnp.asarray(ipv6_batch_words(
        ["2001:db8:bad::1", "2001:db8:feed::1", "fe80::9", "::1"]))
    mask = np.asarray(pf.drop_mask6(addrs))
    assert mask.tolist() == [True, False, True, False]
    pf.delete(["2001:db8:bad::/48"], PrefilterType.PREFIX_DYN_V6)
    mask = np.asarray(pf.drop_mask6(addrs))
    assert mask.tolist() == [False, False, True, False]
    # v4 mask unaffected by v6-only entries
    v4 = jnp.asarray(np.array([0x01020304], np.int32))
    assert not np.asarray(pf.drop_mask(v4)).any()


# ---------------------------------------------------- v6 datapath path

def _dp6():
    """Endpoint 0: ingress allow identity 700 on 443/TCP; egress allow
    identity 9 (the 2001:db8:1::/48 CIDR identity) on 8080; ingress
    proxy redirect for identity 701 on 80."""
    st = PolicyMapState()
    st[PolicyKey(identity=700, dest_port=443, nexthdr=6,
                 direction=INGRESS)] = PolicyMapStateEntry()
    st[PolicyKey(identity=9, dest_port=8080, nexthdr=6,
                 direction=EGRESS)] = PolicyMapStateEntry()
    st[PolicyKey(identity=701, dest_port=80, nexthdr=6,
                 direction=INGRESS)] = PolicyMapStateEntry(proxy_port=14001)
    dp = Datapath(ct_slots=1 << 8, ct_probe=4)
    dp.load_policy([st], revision=1, ipcache_prefixes={})
    dp.load_ipcache6({"2001:db8:7::/64": 700, "2001:db8:8::/64": 701,
                      "2001:db8:1::/48": 9})
    return dp


def test_v6_verdicts_against_oracle():
    dp = _dp6()
    # ingress: allowed identity/port; wrong port; unknown source (WORLD)
    batch = make_full_batch6(
        endpoint=[0, 0, 0, 0],
        saddr=["2001:db8:7::5", "2001:db8:7::5", "9999::1",
               "2001:db8:8::5"],
        daddr=["2001:db8:aa::1"] * 4,
        sport=[10001, 10002, 10003, 10004],
        dport=[443, 444, 443, 80], direction=[0, 0, 0, 0])
    verdict, event, identity, _n = dp.process6(batch, now=50)
    verdict, event, identity = (np.asarray(verdict), np.asarray(event),
                                np.asarray(identity))
    assert identity.tolist() == [700, 700, 2, 701]
    assert verdict[0] == 0 and event[0] == TRACE_TO_LXC
    assert verdict[1] < 0 and event[1] == DROP_POLICY
    assert verdict[2] < 0
    assert verdict[3] == 14001 and event[3] == TRACE_TO_PROXY


def test_v6_cidr_egress_verdict():
    """The v6 CIDR policy path: egress allowed only into the /48."""
    dp = _dp6()
    batch = make_full_batch6(
        endpoint=[0, 0],
        saddr=["2001:db8:aa::1"] * 2,
        daddr=["2001:db8:1:2::9", "2001:db9::9"],
        sport=[20001, 20002], dport=[8080, 8080], direction=[1, 1])
    verdict, _e, identity, _n = dp.process6(batch, now=50)
    assert np.asarray(identity).tolist() == [9, 2]
    assert np.asarray(verdict)[0] == 0
    assert np.asarray(verdict)[1] < 0


def test_v6_prefilter_drop_beats_policy():
    dp = _dp6()
    dp.prefilter.insert(["2001:db8:7::/64"],
                        PrefilterType.PREFIX_DYN_V6)
    dp.reload_prefilter()
    batch = make_full_batch6(
        endpoint=[0], saddr=["2001:db8:7::5"],
        daddr=["2001:db8:aa::1"], sport=[30001], dport=[443],
        direction=[0])
    verdict, event, _i, _n = dp.process6(batch, now=50)
    assert np.asarray(verdict)[0] < 0
    assert np.asarray(event)[0] == DROP_PREFILTER


def test_v6_conntrack_continuation_keeps_proxy_port():
    """Established v6 flows keep their CT verdict: the proxy port
    recorded at create sticks for the connection, and policy removal
    doesn't cut established flows (reference CT semantics)."""
    dp = _dp6()
    mk = lambda sport: make_full_batch6(
        endpoint=[0], saddr=["2001:db8:8::5"],
        daddr=["2001:db8:aa::1"], sport=[sport], dport=[80],
        direction=[0])
    v1, _e, _i, _n = dp.process6(mk(40001), now=50)
    assert np.asarray(v1)[0] == 14001
    # same flow again: established, same proxy port from the CT entry
    v2, _e, _i, _n = dp.process6(mk(40001), now=60)
    assert np.asarray(v2)[0] == 14001
    # v4 CT table is untouched by v6 flows
    assert dp.ct.entry_count() == 0
    assert dp.ct6.entry_count() > 0


def test_v6_overlay_decap_identity():
    """v6 inner packets from the overlay take identity from the tunnel
    key, like v4 (bpf_overlay.c handle_ipv6)."""
    dp = _dp6()
    batch = make_full_batch6(
        endpoint=[0], saddr=["9999::1"], daddr=["2001:db8:aa::1"],
        sport=[50001], dport=[443], direction=[0],
        from_overlay=[1], tunnel_id=[700])
    verdict, _e, identity, _n = dp.process6(batch, now=50)
    # 9999::1 is unknown to the ipcache (would be WORLD) — the tunnel
    # identity decides
    assert np.asarray(identity)[0] == 700
    assert np.asarray(verdict)[0] == 0


# ------------------------------------------------- ICMPv6 / NDP stage

ROUTER6 = "f00d::1"


def _dp6_icmp():
    dp = _dp6()
    dp.set_router_ip6(ROUTER6)
    return dp


def test_icmp6_ns_for_router_answered_ns_for_other_dropped():
    """bpf/lib/icmp6.h __icmp6_handle_ns: NS targeting ROUTER_IP is
    answered with an NA; NS for any other target drops
    (ACTION_UNKNOWN_ICMP6_NS)."""
    from cilium_tpu.datapath.events import (DROP_UNKNOWN_TARGET,
                                            ICMP6_NS_REPLY)
    dp = _dp6_icmp()
    batch = make_full_batch6(
        endpoint=[0, 0],
        saddr=["2001:db8:7::5"] * 2, daddr=["ff02::1:ff00:1"] * 2,
        sport=[0, 0], dport=[0, 0], direction=[1, 1], proto=[58, 58],
        icmp_type=[135, 135],
        nd_target=[ROUTER6, "2001:db8:7::99"])
    verdict, event, _i, _n = dp.process6(batch, now=50)
    verdict, event = np.asarray(verdict), np.asarray(event)
    assert verdict[0] == 0 and event[0] == ICMP6_NS_REPLY
    assert verdict[1] < 0 and event[1] == DROP_UNKNOWN_TARGET


def test_icmp6_echo_to_router_answered_echo_to_peer_polices():
    """Echo request to the router answers locally (terminal action);
    echo to anything else flows through policy like normal traffic —
    here no ICMPv6 rule exists, so it drops."""
    from cilium_tpu.datapath.events import ICMP6_ECHO_REPLY
    dp = _dp6_icmp()
    batch = make_full_batch6(
        endpoint=[0, 0],
        saddr=["2001:db8:7::5"] * 2,
        daddr=[ROUTER6, "2001:db8:aa::1"],
        sport=[0, 0], dport=[0, 0], direction=[1, 1], proto=[58, 58],
        icmp_type=[128, 128])
    verdict, event, _i, _n = dp.process6(batch, now=50)
    verdict, event = np.asarray(verdict), np.asarray(event)
    assert verdict[0] == 0 and event[0] == ICMP6_ECHO_REPLY
    assert verdict[1] < 0


def test_icmp6_answers_do_not_create_ct_state():
    dp = _dp6_icmp()
    batch = make_full_batch6(
        endpoint=[0], saddr=["2001:db8:7::5"], daddr=[ROUTER6],
        sport=[0], dport=[0], direction=[1], proto=[58],
        icmp_type=[128])
    dp.process6(batch, now=50)
    assert dp.ct_entries()[1] == 0


def test_icmp6_prefilter_beats_responder():
    """XDP runs before bpf_lxc: a prefiltered source's NS is dropped,
    never answered."""
    dp = _dp6_icmp()
    dp.prefilter.insert(["2001:db8:7::/64"],
                        PrefilterType.PREFIX_DYN_V6)
    dp.reload_prefilter()
    batch = make_full_batch6(
        endpoint=[0], saddr=["2001:db8:7::5"],
        daddr=["ff02::1:ff00:1"], sport=[0], dport=[0],
        direction=[1], proto=[58], icmp_type=[135],
        nd_target=[ROUTER6])
    verdict, event, _i, _n = dp.process6(batch, now=50)
    assert np.asarray(verdict)[0] < 0
    assert np.asarray(event)[0] == DROP_PREFILTER


def test_icmp6_health_probe_rides_responder():
    """v6 health probes ride the echo responder end-to-end: the
    resolver routes the echo to the datapath owning the address (the
    wire-hop model), that node's responder answers, and the
    synthesized reply bytes validate.  Unknown addresses and nodes
    whose responder doesn't own the address are unreachable."""
    from cilium_tpu.health import PROBE_ICMP, make_icmp6_probe
    dp = _dp6_icmp()
    probe = make_icmp6_probe({ROUTER6: dp}, "2001:db8:7::5")
    ok, lat = probe(PROBE_ICMP, ROUTER6)
    assert ok and lat >= 0.0
    # no node owns this address -> unreachable
    ok, _ = probe(PROBE_ICMP, "2001:db8:aa::1")
    assert not ok
    # a node that does NOT own the probed address can't answer either
    probe_wrong = make_icmp6_probe(
        lambda ip: dp, "2001:db8:7::5")
    ok, _ = probe_wrong(PROBE_ICMP, "2001:db8:aa::1")
    assert not ok
    # v4 targets pass through (layered over another probe_fn)
    assert probe(PROBE_ICMP, "10.0.0.1") == (True, 0.0)


def test_icmp6_reply_synthesis_round_trips():
    """The responder's wire bytes (send_icmp6_ndisc_adv /
    __icmp6_send_echo_reply analogs): valid checksums, correct types,
    flags, and addressing."""
    from cilium_tpu.compiler.lpm import ipv6_to_words
    from cilium_tpu.datapath.icmp6 import (echo_reply,
                                           ndisc_advertisement,
                                           parse_icmp6)
    router = ipv6_to_words(ROUTER6)
    peer = ipv6_to_words("2001:db8:7::5")
    mac = bytes.fromhex("0a1b2c3d4e5f")
    na = parse_icmp6(ndisc_advertisement(router, peer, router, mac))
    assert na["type"] == 136 and na["code"] == 0
    assert na["checksum_ok"]
    assert na["src_words"] == list(router)
    assert na["dst_words"] == list(peer)
    assert na["target_words"] == list(router)
    assert na["tlla"] == mac
    er = parse_icmp6(echo_reply(router, peer, ident=77, seq=3,
                                payload=b"ping"))
    assert er["type"] == 129 and er["checksum_ok"]
    assert er["ident"] == 77 and er["seq"] == 3


def test_v6_counters_accumulate():
    dp = _dp6()
    before = int(np.asarray(dp.counters.packets).sum())
    batch = make_full_batch6(
        endpoint=[0] * 8, saddr=["2001:db8:7::5"] * 8,
        daddr=["2001:db8:aa::1"] * 8,
        sport=list(range(60001, 60009)), dport=[443] * 8,
        direction=[0] * 8)
    dp.process6(batch, now=50)
    after = int(np.asarray(dp.counters.packets).sum())
    assert after - before == 8


# ----------------------------------------------- daemon-level v6 CIDR

def test_daemon_v6_cidr_rule_to_verdict():
    """Author a ToCIDR rule with a v6 prefix through the daemon: the
    CIDR identity is allocated, the ipcache entry lands in the v6
    device LPM, and process6 verdicts follow the rule."""
    import json
    from cilium_tpu.daemon import Daemon
    from cilium_tpu.policy.jsonio import rules_from_json
    from cilium_tpu.utils.option import DaemonConfig

    d = Daemon(config=DaemonConfig())
    ep = d.endpoint_create(1, ipv4="10.44.0.2",
                           labels=["k8s:app=v6client"])
    rev = d.policy_add(rules_from_json(json.dumps([{
        "endpointSelector": {"matchLabels": {"app": "v6client"}},
        "egress": [{"toCIDR": ["2001:db8:55::/48"],
                    "toPorts": [{"ports": [
                        {"port": "443", "protocol": "TCP"}]}]}],
    }])))
    d.wait_for_policy_revision(rev)
    batch = make_full_batch6(
        endpoint=[ep.table_slot] * 3,
        saddr=["2001:db8:aa::1"] * 3,
        daddr=["2001:db8:55::9", "2001:db8:55::9", "2001:db8:66::9"],
        sport=[61001, 61002, 61003], dport=[443, 80, 443],
        direction=[1, 1, 1])
    verdict, _e, identity, _n = d.datapath.process6(batch, now=100)
    verdict = np.asarray(verdict)
    assert verdict[0] == 0, (verdict, np.asarray(identity))
    assert verdict[1] < 0  # wrong port
    assert verdict[2] < 0  # outside the CIDR
    d.shutdown()


# --------------------------------------------------------- v6 service LB

def test_v6_service_lb_dnat_and_rev_nat():
    """lb6 family: VIP -> backend DNAT on the forward path, VIP
    restoration on the reply path (lb.h lb6_local + lb6_rev_nat)."""
    from cilium_tpu.compiler.lpm import ipv6_to_words
    from cilium_tpu.datapath.lb import Backend6, Service6

    st = PolicyMapState()
    # egress allow to the backends' identity on the backend port
    st[PolicyKey(identity=9, dest_port=8443, nexthdr=6,
                 direction=EGRESS)] = PolicyMapStateEntry()
    dp = Datapath(ct_slots=1 << 8, ct_probe=4)
    dp.load_policy([st], revision=1, ipcache_prefixes={})
    dp.load_ipcache6({"2001:db8:1::/48": 9})
    vip = "2001:db8:f::10"
    dp.upsert_service6(Service6(
        vip=ipv6_to_words(vip), port=443,
        backends=[Backend6(ipv6_to_words("2001:db8:1::a"), 8443),
                  Backend6(ipv6_to_words("2001:db8:1::b"), 8443)]))

    batch = make_full_batch6(
        endpoint=[0, 0], saddr=["2001:db8:aa::1"] * 2,
        daddr=[vip, "2001:db8:1::a"],
        sport=[50001, 50002], dport=[443, 8443], direction=[1, 1])
    verdict, _e, _i, nat = dp.process6(batch, now=50)
    verdict = np.asarray(verdict)
    # packet 0: VIP hit -> DNAT to one of the backends on 8443, and
    # the policy verdict ran against the DNAT'd port (allowed)
    assert verdict[0] == 0
    got = np.asarray(nat.daddr)[0].astype(np.uint32).tolist()
    backends = [list(ipv6_to_words("2001:db8:1::a")),
                list(ipv6_to_words("2001:db8:1::b"))]
    assert got in backends
    assert np.asarray(nat.dport)[0] == 8443
    # packet 1: direct-to-backend, untouched
    assert np.asarray(nat.daddr)[1].astype(np.uint32).tolist() == \
        backends[0]

    # reply path: the backend answers; the reply's source is restored
    # to the VIP via the CT-carried rev-NAT index (proof the index was
    # recorded at create)
    chosen = got
    reply = make_full_batch6(
        endpoint=[0], saddr=["::1"],  # placeholder, replaced below
        daddr=["2001:db8:aa::1"], sport=[8443], dport=[50001],
        direction=[0])
    reply = reply._replace(saddr=jnp.asarray(
        np.asarray([chosen], np.uint32).view(np.int32)))
    v2, _e2, _i2, nat2 = dp.process6(reply, now=55)
    restored = np.asarray(nat2.saddr)[0].astype(np.uint32).tolist()
    assert restored == list(ipv6_to_words(vip))
    assert np.asarray(nat2.sport)[0] == 443


def test_daemon_v6_service_upsert_routes_by_family():
    import json
    from cilium_tpu.daemon import Daemon
    from cilium_tpu.policy.jsonio import rules_from_json
    from cilium_tpu.utils.option import DaemonConfig
    d = Daemon(config=DaemonConfig())
    try:
        ep = d.endpoint_create(1, ipv4="10.44.0.3",
                               labels=["k8s:app=v6lb"])
        rev = d.policy_add(rules_from_json(json.dumps([{
            "endpointSelector": {"matchLabels": {"app": "v6lb"}},
            "egress": [{"toCIDR": ["2001:db8:66::/48"]}]}])))
        d.wait_for_policy_revision(rev)
        d.service_upsert("2001:db8:ff::1", 80,
                         [("2001:db8:66::5", 8080)])
        batch = make_full_batch6(
            endpoint=[ep.table_slot], saddr=["2001:db8:aa::1"],
            daddr=["2001:db8:ff::1"], sport=[51001], dport=[80],
            direction=[1])
        verdict, _e, _i, nat = dp_out = d.datapath.process6(batch,
                                                            now=60)
        from cilium_tpu.compiler.lpm import ipv6_to_words
        assert np.asarray(nat.daddr)[0].astype(np.uint32).tolist() == \
            list(ipv6_to_words("2001:db8:66::5"))
        assert np.asarray(nat.dport)[0] == 8080
        # DNAT'd destination is inside the allowed v6 CIDR -> allowed
        assert np.asarray(verdict)[0] == 0
        assert d.service_delete("2001:db8:ff::1", 80)
        v2, _e2, _i2, nat2 = d.datapath.process6(
            batch._replace(sport=jnp.asarray(
                np.asarray([51002], np.int32))), now=61)
        assert np.asarray(nat2.rev_nat)[0] == 0  # no more DNAT
    finally:
        d.shutdown()


def test_lb6_high_port_and_rev_nat_index_stability():
    """Review regressions: NodePort-range ports compile (int32 bit
    pattern), and a deleted service's rev-NAT index is never reused
    (live CT entries may still carry it)."""
    from cilium_tpu.compiler.lpm import ipv6_to_words
    from cilium_tpu.datapath.lb import Backend6, Service6, compile_lb6

    # port >= 32768 must not overflow
    c = compile_lb6([Service6(vip=ipv6_to_words("2001:db8::1"),
                              port=40000,
                              backends=[Backend6(
                                  ipv6_to_words("2001:db8::2"), 8080)])])
    assert c.num_services == 1

    dp = Datapath(ct_slots=1 << 8, ct_probe=4)
    st = PolicyMapState()
    st[PolicyKey(identity=9, dest_port=8443, nexthdr=6,
                 direction=EGRESS)] = PolicyMapStateEntry()
    dp.load_policy([st], revision=1, ipcache_prefixes={})
    mk = lambda ip: Service6(vip=ipv6_to_words(ip), port=443,
                             backends=[Backend6(
                                 ipv6_to_words("2001:db8::b"), 8443)])
    a = mk("2001:db8:a::1")
    dp.upsert_service6(a)
    idx_a = a.rev_nat_index
    assert idx_a > 0
    dp.delete_service6(ipv6_to_words("2001:db8:a::1"), 443)
    b = mk("2001:db8:b::1")
    dp.upsert_service6(b)
    assert b.rev_nat_index != idx_a  # retired index never reused
    # high-port service through the engine path too
    dp.upsert_service6(Service6(vip=ipv6_to_words("2001:db8:c::1"),
                                port=30080,
                                backends=[Backend6(
                                    ipv6_to_words("2001:db8::c"),
                                    8443)]))
    batch = make_full_batch6(
        endpoint=[0], saddr=["2001:db8:aa::1"],
        daddr=["2001:db8:c::1"], sport=[52001], dport=[30080],
        direction=[1])
    _v, _e, _i, nat = dp.process6(batch, now=70)
    from cilium_tpu.compiler.lpm import ipv6_to_words as w6
    assert np.asarray(nat.daddr)[0].astype(np.uint32).tolist() == \
        list(w6("2001:db8::c"))
    assert np.asarray(nat.dport)[0] == 8443


def test_rest_service_dump_includes_v6():
    import json as _json
    import urllib.request
    from cilium_tpu.daemon import Daemon
    from cilium_tpu.daemon.rest import APIServer
    from cilium_tpu.utils.option import DaemonConfig
    d = Daemon(config=DaemonConfig())
    srv = APIServer(d).start()
    try:
        d.service_upsert("10.96.0.50", 80, [("10.0.0.5", 8080)])
        d.service_upsert("2001:db8:ff::2", 443,
                         [("2001:db8:66::7", 8443)])
        with urllib.request.urlopen(srv.base_url + "/service") as r:
            svcs = _json.loads(r.read())
        vips = {s["vip"] for s in svcs}
        assert "10.96.0.50" in vips
        assert "2001:db8:ff::2" in vips
        v6 = [s for s in svcs if s["vip"] == "2001:db8:ff::2"][0]
        assert v6["backends"] == [{"ip": "2001:db8:66::7",
                                   "port": 8443}]
    finally:
        d.shutdown()
