"""Verdict provenance: per-packet matched-rule attribution, compiled-
policy trace replay, and the drift audit.

Covers the acceptance bar of the provenance layer:
- replay-through-compiled-tables verdicts AND tiers are bit-exact
  against the host ``oracle_provenance`` on randomized rule sets
  (3 seeds), and the fused pipeline provenance matches for both
  address families;
- the disabled path is unchanged (no provenance outputs, same
  verdicts);
- provenance propagates into monitor samples, Hubble flow records,
  and the tier/rule metrics;
- an injected compiler corruption is caught by the drift audit in a
  live daemon, fails status() loudly, and bumps policy_drift_total.
"""

import json

import numpy as np
import pytest

from cilium_tpu.compiler.policy_tables import (oracle_provenance,
                                               oracle_verdict)
from cilium_tpu.datapath.engine import Datapath, make_full_batch
from cilium_tpu.datapath.events import (DROP_PREFILTER, TIER_CT_ESTABLISHED,
                                        TIER_DENY, TIER_L3_ALLOW,
                                        TIER_L4_RULE, TIER_L7_REDIRECT,
                                        TIER_LB, TIER_PREFILTER,
                                        format_denied_key, tier_name)
from cilium_tpu.policy.mapstate import (EGRESS, INGRESS, PolicyKey,
                                        PolicyMapState, PolicyMapStateEntry)


def random_states(seed, n_endpoints=4, keys_per_ep=24):
    """Randomized per-endpoint map states mixing every key shape the
    3-stage lookup distinguishes: exact allows, exact redirects,
    L3-only keys, and L4-wildcard (identity=0) keys, both dirs."""
    rng = np.random.default_rng(seed)
    states = []
    for _ in range(n_endpoints):
        st = PolicyMapState()
        for _k in range(keys_per_ep):
            kind = rng.integers(0, 4)
            direction = int(rng.integers(0, 2))
            ident = int(rng.integers(256, 4096))
            port = int(rng.integers(1, 65536))
            proto = int(rng.choice([6, 17]))
            proxy = int(rng.choice([0, 0, 15000 + int(
                rng.integers(0, 100))]))
            if kind == 0:      # exact
                st[PolicyKey(identity=ident, dest_port=port,
                             nexthdr=proto, direction=direction)] = \
                    PolicyMapStateEntry(proxy_port=proxy)
            elif kind == 1:    # L3-only
                st[PolicyKey(identity=ident, direction=direction)] = \
                    PolicyMapStateEntry()
            elif kind == 2:    # L4-wildcard
                st[PolicyKey(identity=0, dest_port=port,
                             nexthdr=proto, direction=direction)] = \
                    PolicyMapStateEntry(proxy_port=proxy)
            else:              # exact allow, no proxy
                st[PolicyKey(identity=ident, dest_port=port,
                             nexthdr=proto, direction=direction)] = \
                    PolicyMapStateEntry()
        states.append(st)
    return states


def sample_tuples(states, seed, n=160):
    """(ep, identity, dport, proto, dir) probes: half aimed at
    installed keys (wildcards get random identities), half random."""
    rng = np.random.default_rng(seed + 1000)
    rows = []
    all_keys = [(e, k) for e, st in enumerate(states) for k in st]
    for _ in range(n // 2):
        e, k = all_keys[int(rng.integers(0, len(all_keys)))]
        ident = k.identity or int(rng.integers(256, 1 << 16))
        rows.append((e, ident, k.dest_port, k.nexthdr, k.direction))
    for _ in range(n - n // 2):
        rows.append((int(rng.integers(0, len(states))),
                     int(rng.integers(0, 1 << 16)),
                     int(rng.integers(0, 65536)),
                     int(rng.choice([6, 17])),
                     int(rng.integers(0, 2))))
    return rows


# ------------------------------------------------------------ replay


class TestReplayOracleParity:
    @pytest.mark.parametrize("seed", [1, 2, 3])
    def test_replay_bit_exact_vs_oracle(self, seed):
        states = random_states(seed)
        dp = Datapath(ct_slots=1 << 10)
        dp.load_policy(states, revision=1, ipcache_prefixes={})
        rows = sample_tuples(states, seed)
        out = dp.policy_replay([r[0] for r in rows],
                               [r[1] for r in rows],
                               [r[2] for r in rows],
                               [r[3] for r in rows],
                               [r[4] for r in rows])
        for (e, ident, dport, proto, dirc), dev in zip(rows, out):
            o_verdict, o_tier, o_key = oracle_provenance(
                states[e], ident, dport, proto, dirc)
            assert dev["verdict"] == o_verdict, (e, ident, dport)
            assert dev["tier"] == o_tier, \
                (dev["tier-name"], tier_name(o_tier), ident, dport)
            if o_key is None:
                assert dev["matched"] is None
            else:
                m = dev["matched"]
                assert (m["identity"], m["dport"], m["proto"],
                        m["direction"]) == (o_key.identity,
                                            o_key.dest_port,
                                            o_key.nexthdr,
                                            o_key.direction)
                assert m["endpoint-slot"] == e

    def test_replay_verdict_matches_plain_oracle(self):
        states = random_states(7)
        dp = Datapath(ct_slots=1 << 10)
        dp.load_policy(states, revision=1, ipcache_prefixes={})
        rows = sample_tuples(states, 7, n=64)
        out = dp.policy_replay([r[0] for r in rows],
                               [r[1] for r in rows],
                               [r[2] for r in rows],
                               [r[3] for r in rows],
                               [r[4] for r in rows])
        for (e, ident, dport, proto, dirc), dev in zip(rows, out):
            assert dev["verdict"] == oracle_verdict(
                states[e], ident, dport, proto, dirc)

    def test_replay_stage_breakdown(self):
        st = PolicyMapState()
        st[PolicyKey(identity=300, dest_port=80, nexthdr=6,
                     direction=EGRESS)] = PolicyMapStateEntry()
        st[PolicyKey(identity=300, direction=EGRESS)] = \
            PolicyMapStateEntry()
        dp = Datapath(ct_slots=1 << 10)
        dp.load_policy([st], revision=1, ipcache_prefixes={})
        row = dp.policy_replay([0], [300], [80], [6], [EGRESS])[0]
        assert row["stages"]["exact"]["found"]
        assert row["stages"]["l3"]["found"]
        assert not row["stages"]["l4_wildcard"]["found"]
        assert row["tier"] == TIER_L4_RULE  # exact wins the chain
        # the L3-only key answers when the exact one is absent
        row = dp.policy_replay([0], [300], [443], [6], [EGRESS])[0]
        assert row["tier"] == TIER_L3_ALLOW
        assert row["matched"]["dport"] == 0


# --------------------------------------------------- fused pipelines


def _v4_datapath(provenance=True):
    st = PolicyMapState()
    st[PolicyKey(identity=300, dest_port=80, nexthdr=6,
                 direction=EGRESS)] = PolicyMapStateEntry()
    st[PolicyKey(identity=301, direction=EGRESS)] = \
        PolicyMapStateEntry()
    st[PolicyKey(identity=0, dest_port=53, nexthdr=17,
                 direction=EGRESS)] = PolicyMapStateEntry(
        proxy_port=15001)
    dp = Datapath(ct_slots=1 << 10)
    if provenance:
        dp.enable_provenance()
    dp.prefilter.insert(["9.9.9.0/24"])
    dp.load_policy([st], revision=1, ipcache_prefixes={
        "10.0.0.0/8": 300, "11.0.0.0/8": 301, "12.0.0.0/8": 999})
    return dp


def _v4_batch():
    return make_full_batch(
        endpoint=[0] * 5,
        saddr=["192.168.0.1", "192.168.0.1", "192.168.0.1",
               "192.168.0.1", "9.9.9.9"],
        daddr=["10.1.1.1", "11.1.1.1", "12.0.0.1", "12.0.0.2",
               "10.1.1.1"],
        sport=[1000] * 5, dport=[80, 443, 53, 9999, 80],
        proto=[6, 6, 17, 6, 6],
        # the prefilter row is INGRESS so its saddr is the peer
        direction=[1, 1, 1, 1, 0])


class TestPipelineProvenanceV4:
    def test_tiers_and_slots(self):
        dp = _v4_datapath()
        v, e, i, n = dp.process(_v4_batch(), now=100)
        prov = dp.last_provenance
        tiers = np.asarray(prov.tier)
        slots = np.asarray(prov.match_slot)
        assert tiers.tolist() == [TIER_L4_RULE, TIER_L3_ALLOW,
                                  TIER_L7_REDIRECT, TIER_DENY,
                                  TIER_PREFILTER]
        assert slots[3] == -1 and slots[4] == -1
        assert (slots[:3] >= 0).all()
        assert np.asarray(e)[4] == DROP_PREFILTER
        # decode names the real compiled keys
        decode = dp.rule_decoder()
        assert decode(int(slots[0]))["identity"] == 300
        assert decode(int(slots[1]))["dport"] == 0
        assert decode(int(slots[2]))["proxy-port"] == 15001

    def test_established_tier_on_second_batch(self):
        dp = _v4_datapath()
        pkt = _v4_batch()
        dp.process(pkt, now=100)
        dp.process(pkt, now=101)
        tiers = np.asarray(dp.last_provenance.tier)
        # allowed/redirected flows ride their CT entry now; the denied
        # and prefiltered rows never created one
        assert tiers.tolist() == [TIER_CT_ESTABLISHED,
                                  TIER_CT_ESTABLISHED,
                                  TIER_CT_ESTABLISHED, TIER_DENY,
                                  TIER_PREFILTER]
        assert (np.asarray(dp.last_provenance.match_slot)[:3]
                == -1).all()

    def test_disabled_path_unchanged(self):
        on = _v4_datapath(provenance=True)
        off = _v4_datapath(provenance=False)
        pkt = _v4_batch()
        v_on, e_on, i_on, _ = on.process(pkt, now=100)
        v_off, e_off, i_off, _ = off.process(pkt, now=100)
        assert off.last_provenance is None
        np.testing.assert_array_equal(np.asarray(v_on),
                                      np.asarray(v_off))
        np.testing.assert_array_equal(np.asarray(e_on),
                                      np.asarray(e_off))

    def test_toggle_reenables_cleanly(self):
        dp = _v4_datapath(provenance=False)
        pkt = _v4_batch()
        dp.process(pkt, now=100)
        dp.enable_provenance()
        dp.process(pkt, now=101)
        assert dp.last_provenance is not None
        dp.disable_provenance()
        dp.process(pkt, now=102)
        assert dp.last_provenance is None

    def test_provenance_with_flow_aggregation(self):
        """Both optional tails fused at once: the unpack indices must
        not collide (flows then provenance)."""
        dp = _v4_datapath()
        dp.enable_flow_aggregation(slots=1 << 8)
        pkt = _v4_batch()
        dp.process(pkt, now=100)
        assert dp.last_provenance is not None
        assert np.asarray(dp.last_provenance.tier).shape[0] == 5
        assert dp.flows.entry_count() > 0


class TestPipelineProvenanceV6:
    def _dp(self):
        st = PolicyMapState()
        st[PolicyKey(identity=400, dest_port=443, nexthdr=6,
                     direction=EGRESS)] = PolicyMapStateEntry()
        dp = Datapath(ct_slots=1 << 10)
        dp.enable_provenance()
        dp.load_policy([st], revision=1, ipcache_prefixes={})
        dp.load_ipcache6({"fd00::/64": 400})
        dp.set_router_ip6("fe80::1")
        return dp

    def test_v6_tiers(self):
        from cilium_tpu.datapath.engine import make_full_batch6
        from cilium_tpu.datapath.pipeline import (ICMP6_NS,
                                                  IPPROTO_ICMPV6)
        dp = self._dp()
        pkt = make_full_batch6(
            endpoint=[0, 0, 0],
            saddr=["fd00::10", "fd00::10", "fd00::10"],
            daddr=["fd00::1", "fd00::1", "fe80::9"],
            sport=[1000] * 3, dport=[443, 9999, 0],
            proto=[6, 6, IPPROTO_ICMPV6],
            icmp_type=[0, 0, ICMP6_NS],
            nd_target=["::", "::", "fe80::1"])
        v, e, i, n = dp.process6(pkt, now=100)
        tiers = np.asarray(dp.last_provenance.tier).tolist()
        assert tiers == [TIER_L4_RULE, TIER_DENY, TIER_LB]
        slots = np.asarray(dp.last_provenance.match_slot)
        assert slots[0] >= 0 and slots[1] == -1 and slots[2] == -1
        # established on replay
        dp.process6(pkt, now=101)
        tiers = np.asarray(dp.last_provenance.tier).tolist()
        assert tiers[0] == TIER_CT_ESTABLISHED
        assert tiers[1] == TIER_DENY and tiers[2] == TIER_LB

    @pytest.mark.parametrize("seed", [11, 12, 13])
    def test_v6_new_flow_verdicts_match_oracle(self, seed):
        """Family parity: a fresh v6 batch's provenance must match
        the host oracle row by row (policy tables are shared, so the
        oracle is the same compute_desired-derived state)."""
        from cilium_tpu.datapath.engine import make_full_batch6
        states = random_states(seed, n_endpoints=2)
        dp = Datapath(ct_slots=1 << 10)
        dp.enable_provenance()
        dp.load_policy(states, revision=1, ipcache_prefixes={})
        dp.load_ipcache6({"fd00::/64": 700})
        rng = np.random.default_rng(seed)
        n = 64
        eps = rng.integers(0, 2, n)
        dports = rng.integers(1, 65536, n)
        protos = rng.choice([6, 17], n)
        pkt = make_full_batch6(
            endpoint=eps, saddr=["fd00::5"] * n, daddr=["fd00::9"] * n,
            sport=rng.integers(1024, 65535, n), dport=dports,
            proto=protos, direction=np.ones(n, np.int32))
        dp.process6(pkt, now=50)
        tiers = np.asarray(dp.last_provenance.tier)
        for i in range(n):
            _v, o_tier, _k = oracle_provenance(
                states[int(eps[i])], 700, int(dports[i]),
                int(protos[i]), EGRESS)
            assert tiers[i] == o_tier, i


# ------------------------------------------- monitor/hubble/metrics


class TestProvenancePropagation:
    def _ingest(self, hub, dp, pkt, now=100):
        v, e, i, n = dp.process(pkt, now=now)
        prov = dp.last_provenance
        hub.ingest_batch(np.asarray(e), np.asarray(pkt.endpoint),
                         np.asarray(i), np.asarray(pkt.dport),
                         np.asarray(pkt.proto), np.asarray(pkt.length),
                         tiers=np.asarray(prov.tier),
                         match_slots=np.asarray(prov.match_slot),
                         rule_of=dp.provenance_rule_of())

    def test_monitor_samples_carry_tier_and_rule(self):
        from cilium_tpu.monitor import MonitorHub
        dp = _v4_datapath()
        hub = MonitorHub()
        self._ingest(hub, dp, _v4_batch())
        events = hub.tail(50)
        by_code = {ev.code: ev for ev in events}
        drop = next(ev for ev in events
                    if ev.is_drop and ev.tier == TIER_DENY)
        assert drop.matched_rule.startswith("deny:identity=")
        assert "tier=deny" in drop.describe()
        assert f"rule={drop.matched_rule}" in drop.describe()
        allowed = next(ev for ev in events if ev.tier == TIER_L4_RULE)
        assert allowed.matched_rule.startswith("identity=300")
        # human-readable reason name, never the raw code
        assert "Prefilter denied" in by_code[DROP_PREFILTER].describe()

    def test_tier_metric_and_top_dropped_rules(self):
        from cilium_tpu.monitor import MonitorHub
        from cilium_tpu.utils.metrics import (POLICY_RULE_DROPS,
                                              POLICY_VERDICT_TIERS)
        dp = _v4_datapath()
        hub = MonitorHub()
        before = POLICY_VERDICT_TIERS.value(labels={"tier": "deny"})
        rule = format_denied_key(999, 9999, 6)
        rule_before = POLICY_RULE_DROPS.value(labels={"rule": rule})
        self._ingest(hub, dp, _v4_batch())
        assert POLICY_VERDICT_TIERS.value(
            labels={"tier": "deny"}) == before + 1
        assert POLICY_RULE_DROPS.value(
            labels={"rule": rule}) == rule_before + 1
        top = hub.top_dropped_rules()
        assert {"rule": rule, "packets": 1} in top

    def test_rule_key_cardinality_cap_under_synthetic_scan(self):
        """The label-cardinality guard: thousands of distinct denied
        keys in ONE batch (a port scan's signature) admit at most
        MAX_RULE_KEYS_PER_BATCH keys into the per-rule counter —
        biggest offenders first — while the aggregate drop counter
        still counts every packet."""
        from cilium_tpu.datapath.events import DROP_NAMES, DROP_POLICY
        from cilium_tpu.monitor import (MAX_RULE_KEYS_PER_BATCH,
                                        MonitorHub)
        from cilium_tpu.utils.metrics import (DROP_COUNT,
                                              POLICY_RULE_DROPS)
        hub = MonitorHub()
        # one loud offender (64 packets on one key) over a scan of
        # 3000 single-packet keys, all denied in the same batch
        n_scan = 3000
        dports = np.concatenate([np.full(64, 9999),
                                 1 + np.arange(n_scan)])
        b = dports.shape[0]
        drops_before = DROP_COUNT.value(
            labels={"reason": DROP_NAMES[DROP_POLICY]})
        rules_before = POLICY_RULE_DROPS.total()
        hub.ingest_batch(np.full(b, DROP_POLICY), np.zeros(b),
                         np.full(b, 777), dports, np.full(b, 6),
                         np.full(b, 100), tiers=np.full(b, TIER_DENY),
                         match_slots=np.full(b, -1))
        # the cap holds: exactly MAX_RULE_KEYS_PER_BATCH distinct keys
        # admitted, the 64-packet offender among them
        top = hub.top_dropped_rules(n=10 * MAX_RULE_KEYS_PER_BATCH)
        assert len(top) == MAX_RULE_KEYS_PER_BATCH
        assert top[0] == {"rule": format_denied_key(777, 9999, 6),
                          "packets": 64}
        assert all(t["packets"] == 1 for t in top[1:])
        # per-rule series: only the admitted keys advanced it
        assert POLICY_RULE_DROPS.total() - rules_before == \
            64 + (MAX_RULE_KEYS_PER_BATCH - 1)
        # aggregate accounting stays accurate: EVERY packet counted
        assert DROP_COUNT.value(
            labels={"reason": DROP_NAMES[DROP_POLICY]}) - \
            drops_before == b
        # a second scan batch admits its own top keys; cumulative
        # top-dropped stays sorted with the offender on top
        hub.ingest_batch(np.full(8, DROP_POLICY), np.zeros(8),
                         np.full(8, 778), np.full(8, 53),
                         np.full(8, 17), np.full(8, 60),
                         tiers=np.full(8, TIER_DENY),
                         match_slots=np.full(8, -1))
        top2 = hub.top_dropped_rules(n=2)
        assert top2[0]["packets"] == 64
        assert top2[1] == {"rule": format_denied_key(778, 53, 17),
                          "packets": 8}

    def test_flow_records_carry_tier(self):
        from cilium_tpu.hubble.filter import FlowFilter
        from cilium_tpu.hubble.observer import FlowObserver
        from cilium_tpu.monitor import MonitorHub
        dp = _v4_datapath()
        hub = MonitorHub()
        obs = FlowObserver(node="n1", datapath=dp)
        obs.attach_monitor(hub)
        self._ingest(hub, dp, _v4_batch())
        denied = obs.get_flows(FlowFilter.from_query({"tier": ["deny"]}),
                               limit=50)
        assert denied and all(f["tier"] == "deny" for f in denied)
        assert denied[0]["matched_rule"].startswith("deny:")
        l4 = obs.get_flows(FlowFilter.from_query(
            {"tier": ["l4-rule"]}), limit=50)
        assert l4 and l4[0]["matched_rule"].startswith("identity=300")


# -------------------------------------------------- drift audit e2e


@pytest.fixture
def live_daemon():
    import jax
    jax.config.update("jax_platforms", "cpu")
    from cilium_tpu.daemon import Daemon
    from cilium_tpu.policy.jsonio import rules_from_json
    from cilium_tpu.utils.option import DaemonConfig
    cfg = DaemonConfig(state_dir="", enable_provenance=True,
                       drift_audit_interval_s=0)
    d = Daemon(config=cfg)
    d.endpoint_create(1, ipv4="10.200.0.10", labels=["k8s:id=web"])
    d.endpoint_create(2, ipv4="10.200.0.11", labels=["k8s:id=db"])
    rules = rules_from_json(json.dumps([{
        "endpointSelector": {"matchLabels": {"id": "db"}},
        "ingress": [{
            "fromEndpoints": [{"matchLabels": {"id": "web"}}],
            "toPorts": [{"ports": [{"port": "5432",
                                    "protocol": "TCP"}]}]}],
        "labels": ["k8s:policy=t"]}]))
    rev = d.policy_add(rules)
    assert d.wait_for_policy_revision(rev, timeout=60)
    yield d
    d.shutdown()


class TestDriftAudit:
    def test_clean_tables_pass_and_corruption_is_caught(self,
                                                        live_daemon):
        from cilium_tpu.utils.metrics import POLICY_DRIFT
        d = live_daemon
        rep = d.run_drift_audit()
        assert rep["status"] == "ok", rep
        assert rep["checked"] > 0 and rep["sc-checked"] > 0
        assert d.status()["provenance"]["drift-audit"]["status"] == "ok"

        # inject a compiler corruption: erase one installed entry from
        # the DEVICE tensors only (host mirror + realized state intact
        # — exactly what a buggy table write would look like)
        drift_before = POLICY_DRIFT.total()
        mgr = d.table_mgr
        rows, cols = np.nonzero(mgr._h_key_meta)
        mgr.key_meta = mgr.key_meta.at[int(rows[0]),
                                       int(cols[0])].set(0)
        d.datapath.refresh_policy()
        rep2 = d.run_drift_audit(samples=256)
        assert rep2["status"] == "FAILING", rep2
        assert rep2["divergences"]
        assert POLICY_DRIFT.total() > drift_before
        st = d.status()["provenance"]["drift-audit"]
        assert st["status"] == "FAILING" and st["divergences"] > 0

    def test_replay_rest_and_cli(self, live_daemon, capsys):
        from cilium_tpu.cli import Client, main as cli_main
        from cilium_tpu.daemon.rest import APIServer
        d = live_daemon
        web = d.endpoints.lookup(1).security_identity
        srv = APIServer(d).start()
        try:
            c = Client(srv.base_url)
            out = c.post("/policy/trace", {
                "endpoint": 2, "identity": web, "dport": 5432,
                "proto": 6, "direction": "ingress"})
            assert out["device"]["tier-name"] == "l4-rule"
            assert not out["drift"]
            assert any(f"PolicyKey(identity={web}, dport=5432" in line
                       for line in out["explanation"])
            # denied tuple explains as tier=deny, exit code 1
            rc = cli_main(["--api", srv.base_url, "policy", "trace",
                           "--replay", "--endpoint", "2",
                           "--identity", str(web), "--dport", "80",
                           "--direction", "ingress"])
            assert rc == 1
            text = capsys.readouterr().out
            assert "tier=deny" in text and "DENIED" in text
            # allowed tuple via labels resolution, exit code 0
            rc = cli_main(["--api", srv.base_url, "policy", "trace",
                           "--replay", "--endpoint", "2", "--src",
                           "k8s:id=web", "--dport", "5432",
                           "--direction", "ingress"])
            assert rc == 0
            text = capsys.readouterr().out
            assert "tier=l4-rule" in text and "ALLOWED" in text
            # unknown endpoint -> 404 surfaces as APIError (exit msg)
            with pytest.raises(SystemExit):
                cli_main(["--api", srv.base_url, "policy", "trace",
                          "--replay", "--endpoint", "99",
                          "--identity", str(web)])
            # last replay + drift report land in debuginfo/bugtool
            info = c.get("/debuginfo")
            assert info["provenance"]["last-replay"] is not None
        finally:
            srv.shutdown()

    def test_drift_report_in_bugtool_archive(self, live_daemon,
                                             tmp_path):
        import tarfile
        from cilium_tpu.bugtool import collect
        d = live_daemon
        d.run_drift_audit()
        path = collect(d, str(tmp_path / "bt.tar.gz"))
        with tarfile.open(path) as tar:
            member = next(m for m in tar.getmembers()
                          if m.name.endswith("provenance.json"))
            data = json.loads(tar.extractfile(member).read())
        assert data["enabled"] is True
        assert data["drift-audit"]["status"] == "ok"
