"""kvstore backend, shared store, and distributed allocator tests.

Models the reference's allocator/kvstore test approach: everything runs
against the in-process backend (pkg/kvstore/dummy.go analog), including
multi-node scenarios via several clients sharing one MemStore.
"""

import threading

import pytest

from cilium_tpu.identity import MINIMAL_NUMERIC_IDENTITY, RESERVED_WORLD
from cilium_tpu.kvstore import (EVENT_CREATE, EVENT_DELETE, EVENT_LIST_DONE,
                                EVENT_MODIFY, InMemoryBackend, KVLockError)
from cilium_tpu.kvstore.allocator import Allocator
from cilium_tpu.kvstore.identity_allocator import (
    DistributedIdentityAllocator, decode_labels, encode_labels)
from cilium_tpu.kvstore.memory import MemStore
from cilium_tpu.kvstore.store import SharedStore
from cilium_tpu.labels import Labels, parse_label


def two_clients():
    store = MemStore()
    return InMemoryBackend(store), InMemoryBackend(store)


class TestBackend:
    def test_set_get_delete(self):
        b = InMemoryBackend()
        assert b.get("a") is None
        b.set("a", b"1")
        assert b.get("a") == b"1"
        b.delete("a")
        assert b.get("a") is None

    def test_create_only_is_atomic_between_clients(self):
        a, b = two_clients()
        assert a.create_only("k", b"a")
        assert not b.create_only("k", b"b")
        assert b.get("k") == b"a"

    def test_create_if_exists(self):
        b = InMemoryBackend()
        assert not b.create_if_exists("master", "slave", b"v")
        b.set("master", b"m")
        assert b.create_if_exists("master", "slave", b"v")
        assert b.get("slave") == b"v"
        # second create of an existing slave fails
        assert not b.create_if_exists("master", "slave", b"v2")

    def test_list_prefix(self):
        b = InMemoryBackend()
        b.set("p/x", b"1")
        b.set("p/y", b"2")
        b.set("q/z", b"3")
        assert b.list_prefix("p/") == {"p/x": b"1", "p/y": b"2"}
        b.delete_prefix("p/")
        assert b.list_prefix("p/") == {}

    def test_watch_sees_changes(self):
        a, b = two_clients()
        w = a.watch("pfx/")
        b.set("pfx/k", b"v")
        b.set("pfx/k", b"v2")
        b.delete("pfx/k")
        b.set("other/k", b"x")  # not under the prefix
        evs = [w.next_event(timeout=1.0) for _ in range(3)]
        assert [(e.typ, e.key) for e in evs] == [
            (EVENT_CREATE, "pfx/k"), (EVENT_MODIFY, "pfx/k"),
            (EVENT_DELETE, "pfx/k")]
        assert w.next_event(timeout=0.05) is None
        w.stop()

    def test_list_and_watch_replays_then_streams(self):
        a, b = two_clients()
        b.set("s/1", b"one")
        w = a.list_and_watch("s/")
        first = w.next_event(timeout=1.0)
        assert (first.typ, first.key, first.value) == \
            (EVENT_CREATE, "s/1", b"one")
        assert w.next_event(timeout=1.0).typ == EVENT_LIST_DONE
        b.set("s/2", b"two")
        assert w.next_event(timeout=1.0).key == "s/2"
        w.stop()

    def test_lease_keys_vanish_when_session_dies(self):
        a, b = two_clients()
        w = b.watch("lease/")
        a.set("lease/mine", b"v", lease=True)
        a.set("lease/plain", b"v")
        assert w.next_event(timeout=1.0).typ == EVENT_CREATE
        assert w.next_event(timeout=1.0).typ == EVENT_CREATE
        a.expire_now()  # node failure
        ev = w.next_event(timeout=1.0)
        assert (ev.typ, ev.key) == (EVENT_DELETE, "lease/mine")
        assert b.get("lease/plain") == b"v"
        w.stop()

    def test_lock_mutual_exclusion_and_timeout(self):
        a, b = two_clients()
        lock = a.lock_path("locks/x", timeout=1.0)
        with pytest.raises(KVLockError):
            b.lock_path("locks/x", timeout=0.1)
        lock.unlock()
        with b.lock_path("locks/x", timeout=1.0):
            pass

    def test_lock_released_on_session_death(self):
        a, b = two_clients()
        a.lock_path("locks/y", timeout=1.0)
        a.expire_now()
        with b.lock_path("locks/y", timeout=1.0):
            pass


class TestSharedStore:
    def test_two_nodes_converge(self):
        a, b = two_clients()
        seen = {}
        sa = SharedStore(a, "cilium/state/nodes/v1")
        sb = SharedStore(b, "cilium/state/nodes/v1",
                         on_update=lambda n, v: seen.__setitem__(n, v))
        assert sa.wait_synced() and sb.wait_synced()
        sa.update_local("node1", {"ip": "10.0.0.1"})
        deadline = threading.Event()
        for _ in range(100):
            if sb.snapshot().get("node1") == {"ip": "10.0.0.1"}:
                break
            deadline.wait(0.01)
        assert sb.snapshot()["node1"] == {"ip": "10.0.0.1"}
        assert seen["node1"] == {"ip": "10.0.0.1"}
        sa.delete_local("node1")
        for _ in range(100):
            if "node1" not in sb.snapshot():
                break
            deadline.wait(0.01)
        assert "node1" not in sb.snapshot()
        sa.close()
        sb.close()


class TestAllocator:
    def test_same_key_same_id_across_nodes(self):
        a, b = two_clients()
        alloc_a = Allocator(a, "cilium/state/identities/v1", "node-a",
                            256, 65535, seed=1)
        alloc_b = Allocator(b, "cilium/state/identities/v1", "node-b",
                            256, 65535, seed=2)
        id_a, new_a = alloc_a.allocate("app=foo")
        id_b, new_b = alloc_b.allocate("app=foo")
        assert id_a == id_b
        assert new_a and not new_b
        assert 256 <= id_a <= 65535

    def test_different_keys_different_ids(self):
        alloc = Allocator(InMemoryBackend(), "pfx", "n", 256, 65535, seed=3)
        ids = {alloc.allocate(f"key-{i}")[0] for i in range(50)}
        assert len(ids) == 50

    def test_refcount_release_and_gc(self):
        a, b = two_clients()
        alloc_a = Allocator(a, "pfx", "node-a", 256, 65535, seed=4)
        alloc_b = Allocator(b, "pfx", "node-b", 256, 65535, seed=5)
        id_, _ = alloc_a.allocate("k")
        alloc_b.allocate("k")
        alloc_a.allocate("k")  # refcount 2 on node-a
        # master survives while any slave key exists
        assert not alloc_a.release("k")
        assert alloc_a.release("k")
        assert alloc_a.run_gc() == 0  # node-b still holds it
        assert alloc_b.release("k")
        assert alloc_b.run_gc() == 1  # masterless now; reclaimed
        assert a.get(f"pfx/id/{id_}") is None

    def test_lease_expiry_frees_ids_for_gc(self):
        a, b = two_clients()
        alloc_a = Allocator(a, "pfx", "node-a", 256, 65535, seed=6)
        alloc_b = Allocator(b, "pfx", "node-b", 256, 65535, seed=7)
        alloc_a.allocate("k")
        a.expire_now()  # node-a dies; its slave key lease reaps
        assert alloc_b.run_gc() == 1

    def test_watch_cache_feeds_other_nodes(self):
        a, b = two_clients()
        alloc_a = Allocator(a, "pfx", "node-a", 256, 65535, seed=8)
        alloc_b = Allocator(b, "pfx", "node-b", 256, 65535, seed=9)
        id_, _ = alloc_a.allocate("shared")
        for _ in range(100):
            if alloc_b.get("shared") == id_:
                break
            threading.Event().wait(0.01)
        assert alloc_b.get("shared") == id_
        assert alloc_b.get_by_id(id_) == "shared"

    def test_concurrent_allocation_converges(self):
        store = MemStore()
        results = {}

        def worker(name):
            alloc = Allocator(InMemoryBackend(store), "pfx", name,
                              256, 65535)
            results[name] = alloc.allocate("contended")[0]

        threads = [threading.Thread(target=worker, args=(f"n{i}",))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(set(results.values())) == 1


class TestDistributedIdentityAllocator:
    def labels(self, *strs):
        return Labels.from_labels(parse_label(s) for s in strs)

    def test_label_key_roundtrip(self):
        lbls = self.labels("k8s:app=web", "k8s:io.kubernetes.pod.namespace=x",
                           "cidr:10.0.0.0/8")
        assert decode_labels(encode_labels(lbls)).sorted_list() == \
            lbls.sorted_list()

    def test_same_labels_same_identity_across_nodes(self):
        a, b = two_clients()
        da = DistributedIdentityAllocator(a, "node-a", seed=1)
        db = DistributedIdentityAllocator(b, "node-b", seed=2)
        lbls = self.labels("k8s:app=web")
        ia, new_a = da.allocate(lbls)
        ib, new_b = db.allocate(lbls)
        assert ia.id == ib.id >= MINIMAL_NUMERIC_IDENTITY
        assert new_a and not new_b
        assert db.lookup_by_id(ia.id).labels.sorted_list() == \
            lbls.sorted_list()

    def test_reserved_short_circuit(self):
        da = DistributedIdentityAllocator(InMemoryBackend(), "n")
        ident, is_new = da.allocate(self.labels("reserved:world"))
        assert ident.id == RESERVED_WORLD and not is_new

    def test_cluster_id_bits(self):
        da = DistributedIdentityAllocator(InMemoryBackend(), "n",
                                          cluster_id=3, seed=3)
        ident, _ = da.allocate(self.labels("k8s:app=x"))
        assert ident.id >> 16 == 3
        assert da.lookup_by_id(ident.id) is not None

    def test_change_events(self):
        a, b = two_clients()
        events = []
        DistributedIdentityAllocator(
            b, "node-b", on_change=lambda t, i: events.append((t, i.id)))
        da = DistributedIdentityAllocator(a, "node-a", seed=4)
        ident, _ = da.allocate(self.labels("k8s:app=ev"))
        da.release(ident)
        da.run_gc()
        for _ in range(100):
            if ("delete", ident.id) in events:
                break
            threading.Event().wait(0.01)
        assert ("add", ident.id) in events
        assert ("delete", ident.id) in events

    def test_snapshot_feeds_identity_cache(self):
        from cilium_tpu.identity import IdentityCache
        da = DistributedIdentityAllocator(InMemoryBackend(), "n", seed=5)
        ident, _ = da.allocate(self.labels("k8s:app=cache"))
        cache = IdentityCache.snapshot(da)
        assert ident.id in cache
        assert RESERVED_WORLD in cache
