"""Dense broadcast-compare verdict engine vs the scalar oracle and the
hash engine — both the jnp path and the Pallas kernel (interpret mode
on CPU).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from cilium_tpu.compiler.policy_tables import (compile_endpoints,
                                               oracle_verdict)
from cilium_tpu.ops.dense_verdict import (HAS_PALLAS, DenseVerdictEngine,
                                          compile_dense,
                                          dense_verdict_pallas,
                                          dense_verdict_step)
from cilium_tpu.policy.mapstate import (EGRESS, INGRESS, PolicyKey,
                                        PolicyMapState,
                                        PolicyMapStateEntry)


def _random_states(n_endpoints=4, n_rules=24, seed=5):
    rng = np.random.default_rng(seed)
    states = []
    idents = rng.integers(256, 400, 16)
    ports = rng.integers(1, 2048, 16)
    for _ in range(n_endpoints):
        st = PolicyMapState()
        for _ in range(n_rules):
            st[PolicyKey(identity=int(rng.choice(idents)),
                         dest_port=int(rng.choice(ports)), nexthdr=6,
                         direction=int(rng.integers(0, 2)))] = \
                PolicyMapStateEntry(
                    proxy_port=int(rng.integers(0, 2) * 11000))
        # L3-only + L4-wildcard entries exercise stages 2/3
        st[PolicyKey(identity=int(rng.choice(idents)),
                     direction=INGRESS)] = PolicyMapStateEntry()
        st[PolicyKey(identity=0, dest_port=80, nexthdr=6,
                     direction=INGRESS)] = \
            PolicyMapStateEntry(proxy_port=15001)
        states.append(st)
    return states


def _random_queries(states, batch, seed=6):
    rng = np.random.default_rng(seed)
    n_ep = len(states)
    return (rng.integers(0, n_ep, batch).astype(np.int32),
            rng.integers(250, 410, batch).astype(np.int32),
            rng.choice(np.r_[rng.integers(1, 2048, 32), 80],
                       batch).astype(np.int32),
            np.full(batch, 6, np.int32),
            rng.integers(0, 2, batch).astype(np.int32),
            np.full(batch, 256, np.int32))


def test_dense_jnp_matches_oracle_and_counters():
    states = _random_states()
    eng = DenseVerdictEngine(states)
    ep, ident, dport, proto, dirn, length = _random_queries(states, 1024)
    verdict = np.asarray(eng(ep, ident, dport, proto, dirn, length))
    n_hits = 0
    for i in range(1024):
        want = oracle_verdict(states[ep[i]], int(ident[i]),
                              int(dport[i]), int(proto[i]), int(dirn[i]))
        assert verdict[i] == want, (i, want, verdict[i])
        if want != -1:
            n_hits += 1
    # counters: every non-drop packet attributed to exactly one entry
    assert int(np.asarray(eng.counters_packets).sum()) == n_hits
    assert int(np.asarray(eng.counters_bytes).sum()) == n_hits * 256


@pytest.mark.skipif(not HAS_PALLAS, reason="pallas unavailable")
def test_dense_pallas_matches_jnp():
    states = _random_states(seed=7)
    tables = compile_dense(states)
    ep, ident, dport, proto, dirn, length = _random_queries(states, 512,
                                                            seed=8)
    arr = lambda x: jnp.asarray(x)
    v_ref, cpk_ref, cby_ref = dense_verdict_step(
        tables, jnp.zeros_like(tables.ep, jnp.uint32),
        jnp.zeros_like(tables.ep, jnp.uint32), arr(ep), arr(ident),
        arr(dport), arr(proto), arr(dirn), arr(length))
    v_pl, cpk_pl, cby_pl = dense_verdict_pallas(
        tables, arr(ep), arr(ident), arr(dport), arr(proto), arr(dirn),
        arr(length), block_b=128, interpret=True)
    np.testing.assert_array_equal(np.asarray(v_ref), np.asarray(v_pl))
    np.testing.assert_array_equal(np.asarray(cpk_ref),
                                  np.asarray(cpk_pl).astype(np.uint32))
    np.testing.assert_array_equal(np.asarray(cby_ref),
                                  np.asarray(cby_pl).astype(np.uint32))


@pytest.mark.skipif(not HAS_PALLAS, reason="pallas unavailable")
def test_dense_engine_pallas_path():
    states = _random_states(seed=9)
    eng = DenseVerdictEngine(states, use_pallas=True, block_b=128)
    assert eng.use_pallas
    ep, ident, dport, proto, dirn, length = _random_queries(states, 256,
                                                            seed=10)
    verdict = np.asarray(eng(ep, ident, dport, proto, dirn, length))
    for i in range(256):
        want = oracle_verdict(states[ep[i]], int(ident[i]),
                              int(dport[i]), 6, int(dirn[i]))
        assert verdict[i] == want
    # counters accumulated through the pallas path too
    assert int(np.asarray(eng.counters_packets).sum()) == \
        int((verdict != -1).sum())


def test_dense_matches_hash_engine():
    """Dense and hash engines must agree verdict-for-verdict on the
    same map states — the parity the bench's winner-selection relies
    on."""
    from cilium_tpu.datapath.verdict import VerdictEngine, \
        make_packet_batch
    states = _random_states(seed=12)
    dense = DenseVerdictEngine(states)
    hash_eng = VerdictEngine(compile_endpoints(states, revision=1))
    ep, ident, dport, proto, dirn, length = _random_queries(states, 512,
                                                            seed=13)
    dense_v = np.asarray(dense(ep, ident, dport, proto, dirn, length))
    hash_v = np.asarray(hash_eng(make_packet_batch(
        endpoint=ep, identity=ident, dport=dport, proto=proto,
        direction=dirn, length=length)))
    np.testing.assert_array_equal(dense_v, hash_v)


def test_dense_empty_and_padding():
    eng = DenseVerdictEngine([PolicyMapState()])
    v = np.asarray(eng(np.zeros(4, np.int32), np.full(4, 300, np.int32),
                       np.full(4, 80, np.int32), np.full(4, 6, np.int32),
                       np.zeros(4, np.int32), np.full(4, 100, np.int32)))
    assert (v == -1).all()
    # padding rows (ep=-1) can never match a real endpoint
    assert int(np.asarray(eng.counters_packets).sum()) == 0


def test_dense_lpm_matches_oracle():
    from cilium_tpu.compiler.lpm import ipv4_to_u32, oracle_lpm
    from cilium_tpu.ops.dense_verdict import (compile_dense_lpm,
                                              dense_lpm_lookup)
    prefixes = {"10.0.0.0/8": 100, "10.1.0.0/16": 200,
                "10.1.2.0/24": 300, "10.1.2.3/32": 400,
                "0.0.0.0/0": 2, "192.168.0.0/16": 500}
    lpm = compile_dense_lpm(prefixes)
    queries = ["10.1.2.3", "10.1.2.9", "10.1.9.9", "10.9.9.9",
               "192.168.1.1", "8.8.8.8"]
    addrs = jnp.asarray(np.array([ipv4_to_u32(q) for q in queries],
                                 np.uint32).view(np.int32))
    found, value = dense_lpm_lookup(lpm, addrs)
    assert np.asarray(found).all()  # 0.0.0.0/0 catches everything
    for q, v in zip(queries, np.asarray(value)):
        assert oracle_lpm(prefixes, q) == int(v), q


def test_dense_datapath_step_end_to_end():
    from cilium_tpu.compiler.lpm import ipv4_to_u32
    from cilium_tpu.ops.dense_verdict import (compile_dense_lpm,
                                              dense_datapath_step)
    # identity 300 lives at 10.1.0.0/16; endpoint 0 allows it on 80/TCP
    st = PolicyMapState()
    st[PolicyKey(identity=300, dest_port=80, nexthdr=6,
                 direction=INGRESS)] = PolicyMapStateEntry()
    tables = compile_dense([st])
    lpm = compile_dense_lpm({"10.1.0.0/16": 300})
    n = tables.ep.shape[0]
    addrs = jnp.asarray(np.array(
        [ipv4_to_u32("10.1.2.3"), ipv4_to_u32("8.8.8.8")],
        np.uint32).view(np.int32))
    z = lambda v: jnp.asarray(np.array(v, np.int32))
    verdict, identity, cpk, cby = dense_datapath_step(
        tables, lpm, jnp.zeros(n, jnp.uint32), jnp.zeros(n, jnp.uint32),
        z([0, 0]), addrs, z([80, 80]), z([6, 6]), z([0, 0]),
        z([256, 256]))
    v = np.asarray(verdict)
    assert v[0] == 0       # known identity allowed
    assert v[1] == -1      # world dropped
    ids = np.asarray(identity)
    assert ids[0] == 300 and ids[1] == 2
    assert int(np.asarray(cpk).sum()) == 1


@pytest.mark.skipif(not HAS_PALLAS, reason="pallas unavailable")
def test_dense_pallas_multi_tile_parity():
    """Entry axis larger than one tile: the 2-D grid must accumulate
    stage partials across tiles and still match the jnp path exactly
    (verdicts AND per-entry counters)."""
    states = _random_states(n_endpoints=16, n_rules=100, seed=12)
    tables = compile_dense(states)
    n = int(tables.ep.shape[0])
    tile_n = 256
    assert n > 2 * tile_n  # genuinely multi-tile
    ep, ident, dport, proto, dirn, length = _random_queries(states, 512,
                                                            seed=13)
    arr = lambda x: jnp.asarray(x)
    v_ref, cpk_ref, cby_ref = dense_verdict_step(
        tables, jnp.zeros_like(tables.ep, jnp.uint32),
        jnp.zeros_like(tables.ep, jnp.uint32), arr(ep), arr(ident),
        arr(dport), arr(proto), arr(dirn), arr(length))
    v_pl, cpk_pl, cby_pl = dense_verdict_pallas(
        tables, arr(ep), arr(ident), arr(dport), arr(proto), arr(dirn),
        arr(length), block_b=128, tile_n=tile_n, interpret=True)
    np.testing.assert_array_equal(np.asarray(v_ref), np.asarray(v_pl))
    np.testing.assert_array_equal(np.asarray(cpk_ref),
                                  np.asarray(cpk_pl).astype(np.uint32))
    np.testing.assert_array_equal(np.asarray(cby_ref),
                                  np.asarray(cby_pl).astype(np.uint32))


@pytest.mark.skipif(not HAS_PALLAS, reason="pallas unavailable")
def test_dense_pallas_non_tile_multiple_padding():
    """N not a multiple of tile_n: padding rows (ep=-1) must never
    match and the counter scatter must stay within the real N."""
    states = _random_states(n_endpoints=3, n_rules=50, seed=14)
    tables = compile_dense(states)
    n = int(tables.ep.shape[0])
    tile_n = 384  # LANE-padded N=384*k only by luck; force check
    ep, ident, dport, proto, dirn, length = _random_queries(states, 256,
                                                            seed=15)
    arr = lambda x: jnp.asarray(x)
    v_ref, cpk_ref, cby_ref = dense_verdict_step(
        tables, jnp.zeros_like(tables.ep, jnp.uint32),
        jnp.zeros_like(tables.ep, jnp.uint32), arr(ep), arr(ident),
        arr(dport), arr(proto), arr(dirn), arr(length))
    v_pl, cpk_pl, cby_pl = dense_verdict_pallas(
        tables, arr(ep), arr(ident), arr(dport), arr(proto), arr(dirn),
        arr(length), block_b=256, tile_n=tile_n, interpret=True)
    assert cpk_pl.shape[0] == n
    np.testing.assert_array_equal(np.asarray(v_ref), np.asarray(v_pl))
    np.testing.assert_array_equal(np.asarray(cpk_ref),
                                  np.asarray(cpk_pl).astype(np.uint32))
