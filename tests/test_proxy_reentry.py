"""Proxy-identity re-entry: proxied flows keep their original identity.

Reference: bpf/bpf_netdev.c:128-146 — packets leaving the L7 proxy
toward the upstream carry the ORIGINAL source identity in the skb mark
(MARK_MAGIC_PROXY, set via SO_MARK on the proxy's upstream socket);
the netdev ingress program reads it back instead of resolving the
proxy host's address, so the upstream leg of a proxied connection is
policy-checked as its true source, not as WORLD.

Here the mark is the ``mark_identity`` field on the packet batch, and
the SocketProxy registers each upstream leg's local address with the
source identity (SO_MARK analog) for the re-entry path to read.
"""

import socket
import socketserver
import threading
import time

import numpy as np
import pytest

from cilium_tpu.datapath.engine import (Datapath, make_full_batch,
                                        make_full_batch6)
from cilium_tpu.l7.socket_proxy import ListenerContext, SocketProxy
from cilium_tpu.l7.parser import PortRuleL7
from cilium_tpu.policy.mapstate import (INGRESS, PolicyKey,
                                        PolicyMapState,
                                        PolicyMapStateEntry)


def _dp():
    """Upstream endpoint (slot 0): ingress allows only identity 777 on
    9000/TCP.  The proxy host's address is NOT in the ipcache, so
    unmarked re-entry traffic classifies as WORLD and is denied."""
    st = PolicyMapState()
    st[PolicyKey(identity=777, dest_port=9000, nexthdr=6,
                 direction=INGRESS)] = PolicyMapStateEntry()
    dp = Datapath(ct_slots=1 << 8, ct_probe=4)
    dp.load_policy([st], revision=1, ipcache_prefixes={})
    return dp


def test_mark_identity_wins_over_ipcache():
    dp = _dp()
    batch = make_full_batch(
        endpoint=[0, 0], saddr=["127.0.0.1", "127.0.0.1"],
        daddr=["10.5.0.2"] * 2, sport=[41001, 41002],
        dport=[9000, 9000], direction=[0, 0],
        mark_identity=[777, 0])
    verdict, _e, identity, _n = dp.process(batch, now=50)
    identity = np.asarray(identity)
    verdict = np.asarray(verdict)
    # marked packet: original identity, allowed
    assert identity[0] == 777 and verdict[0] == 0
    # unmarked packet from the same (proxy) address: WORLD, denied —
    # exactly the misclassification the mark exists to prevent
    assert identity[1] == 2 and verdict[1] < 0


def test_mark_identity_v6():
    dp = _dp()
    batch = make_full_batch6(
        endpoint=[0, 0], saddr=["fe80::1", "fe80::1"],
        daddr=["2001:db8::2"] * 2, sport=[41003, 41004],
        dport=[9000, 9000], direction=[0, 0],
        mark_identity=[777, 0])
    verdict, _e, identity, _n = dp.process6(batch, now=50)
    assert np.asarray(identity).tolist() == [777, 2]
    assert np.asarray(verdict)[0] == 0
    assert np.asarray(verdict)[1] < 0


def test_batches_without_mark_unchanged():
    dp = _dp()
    batch = make_full_batch(
        endpoint=[0], saddr=["127.0.0.1"], daddr=["10.5.0.2"],
        sport=[41005], dport=[9000], direction=[0])
    assert batch.mark_identity is None
    _v, _e, identity, _n = dp.process(batch, now=50)
    assert np.asarray(identity)[0] == 2


# ------------------------------------------------------ e2e via proxy

class _Upstream(socketserver.ThreadingTCPServer):
    """Records the peer address of every accepted connection — the
    'netdev ingress' vantage point of the upstream leg."""

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self):
        self.peers = []
        super().__init__(("127.0.0.1", 0), _UpHandler)
        threading.Thread(target=self.serve_forever, daemon=True).start()

    @property
    def port(self):
        return self.server_address[1]


class _UpHandler(socketserver.BaseRequestHandler):
    def handle(self):
        self.server.peers.append(self.client_address)
        while True:
            try:
                data = self.request.recv(65536)
            except OSError:
                return
            if not data:
                return
            self.request.sendall(b"END\r\n")


def test_reentry_identity_through_socket_proxy():
    """The full loop: client -> proxy (identity 777 resolved for the
    connection) -> upstream.  At the upstream's ingress vantage point,
    the flow's mark (read back from the proxy, SO_MARK analog) feeds
    mark_identity, and the datapath classifies the proxied flow as 777
    — where the unmarked path would yield WORLD and deny."""
    dp = _dp()
    upstream = _Upstream()
    proxy = SocketProxy()
    ctx = ListenerContext(
        redirect_id="9:ingress:TCP:9000", parser_type="memcache",
        orig_dst=lambda peer: ("127.0.0.1", upstream.port),
        l7_rules=lambda peer: [PortRuleL7.from_dict(
            {"command": "get", "key": "*"})],
        identities=lambda peer: (777, 888))
    port = proxy.start_listener(0, ctx)
    c = socket.create_connection(("127.0.0.1", port), timeout=5)
    c.settimeout(5)
    try:
        c.sendall(b"get a\r\n")
        buf = b""
        deadline = time.time() + 5
        while b"END" not in buf and time.time() < deadline:
            buf += c.recv(65536)
        assert b"END" in buf
        # the upstream saw the proxy's leg; its peer address is the
        # proxy's upstream-local address — read the mark back
        assert upstream.peers, "upstream never saw the connection"
        leg = upstream.peers[-1]
        mark = proxy.mark_for(leg)
        assert mark == 777
        # netdev ingress classification of the upstream leg
        batch = make_full_batch(
            endpoint=[0], saddr=[leg[0]], daddr=["10.5.0.2"],
            sport=[leg[1]], dport=[9000], direction=[0],
            mark_identity=[mark])
        verdict, _e, identity, _n = dp.process(batch, now=60)
        assert np.asarray(identity)[0] == 777
        assert np.asarray(verdict)[0] == 0
        # without the mark the same packet is WORLD -> denied
        batch2 = make_full_batch(
            endpoint=[0], saddr=[leg[0]], daddr=["10.5.0.2"],
            sport=[leg[1] + 1], dport=[9000], direction=[0])
        v2, _e2, i2, _n2 = dp.process(batch2, now=60)
        assert np.asarray(i2)[0] == 2 and np.asarray(v2)[0] < 0
    finally:
        c.close()
        proxy.shutdown()
        upstream.shutdown()
    # mark is cleaned up when the connection ends
    deadline = time.time() + 5
    while proxy.mark_for(leg) and time.time() < deadline:
        time.sleep(0.05)
    assert proxy.mark_for(leg) == 0
