"""etcd-protocol kvstore backend against the in-repo mini-etcd
(round-5 VERDICT #6).

The own-TCP backend proved the semantics; this proves PORTABILITY:
``BackendOperations`` running over a second, production-shaped wire —
the etcd v3 JSON gateway (pkg/kvstore/etcd.go analog: leases +
keepalives, txn-based CreateOnly/CreateIfExists, prefix watches,
lease-bound locks).  The suite tiers mirror test_remote_kvstore.py:
unit ops over the wire, the distributed allocator across two clients,
and the kill -9 -> lease lapse -> GC reclamation story with full agent
subprocesses.
"""

import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from cilium_tpu.kvstore.backend import (EVENT_CREATE, EVENT_DELETE,
                                        EVENT_LIST_DONE, EVENT_MODIFY,
                                        KVLockError)
from cilium_tpu.kvstore.etcd import EtcdBackend
from cilium_tpu.kvstore.mini_etcd import MiniEtcd

AGENT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "agent_proc.py")


@pytest.fixture()
def server():
    srv = MiniEtcd(reap_interval=0.1).start()
    yield srv
    srv.shutdown()


@pytest.fixture()
def client(server):
    c = EtcdBackend(port=server.port, lease_ttl=5.0)
    yield c
    c.close()


# ------------------------------------------------------------- unit tier

def test_basic_ops_over_etcd_wire(server, client):
    assert client.get("a") is None
    client.set("a", b"1")
    assert client.get("a") == b"1"
    client.set("dir/x", b"x")
    client.set("dir/y", b"y")
    assert client.list_prefix("dir/") == {"dir/x": b"x", "dir/y": b"y"}
    assert client.get_prefix("dir/") == b"x"
    client.delete("dir/x")
    assert client.list_prefix("dir/") == {"dir/y": b"y"}
    client.delete_prefix("dir/")
    assert client.list_prefix("dir/") == {}


def test_atomic_ops_between_clients(server, client):
    other = EtcdBackend(port=server.port, lease_ttl=5.0)
    try:
        assert client.create_only("ck", b"first")
        assert not other.create_only("ck", b"second")
        assert other.get("ck") == b"first"
        # create_if_exists: condition key present vs absent
        assert client.create_if_exists("ck", "dep", b"v")
        assert other.get("dep") == b"v"
        assert not client.create_if_exists("missing", "dep2", b"v")
        assert other.get("dep2") is None
    finally:
        other.close()


def test_lease_keys_vanish_when_client_dies(server):
    short = EtcdBackend(port=server.port, lease_ttl=1.0)
    observer = EtcdBackend(port=server.port, lease_ttl=30.0)
    try:
        short.set("leased/a", b"1", lease=True)
        short.set("plain/b", b"2")
        assert observer.get("leased/a") == b"1"
        # kill the keepalive without revoking (process-death model)
        short._closed.set()
        deadline = time.time() + 10
        while time.time() < deadline and \
                observer.get("leased/a") is not None:
            time.sleep(0.1)
        assert observer.get("leased/a") is None, \
            "lease-backed key must vanish after TTL"
        assert observer.get("plain/b") == b"2"
    finally:
        observer.close()
        short.close()


def test_watch_sees_other_clients_writes(server, client):
    other = EtcdBackend(port=server.port, lease_ttl=5.0)
    try:
        w = client.watch("w/")
        time.sleep(0.2)  # stream established
        other.set("w/k", b"v1")
        other.set("w/k", b"v2")
        other.delete("w/k")
        evs = [w.next_event(timeout=5) for _ in range(3)]
        assert [e.typ for e in evs] == [EVENT_CREATE, EVENT_MODIFY,
                                        EVENT_DELETE]
        assert evs[0].key == "w/k" and evs[0].value == b"v1"
        assert evs[1].value == b"v2"
        w.stop()
    finally:
        other.close()


def test_list_and_watch_replays_then_streams(server, client):
    client.set("lw/a", b"1")
    client.set("lw/b", b"2")
    w = client.list_and_watch("lw/")
    replay = {w.next_event(timeout=5).key for _ in range(2)}
    assert replay == {"lw/a", "lw/b"}
    assert w.next_event(timeout=5).typ == EVENT_LIST_DONE
    client.set("lw/c", b"3")
    ev = w.next_event(timeout=5)
    assert ev.typ == EVENT_CREATE and ev.key == "lw/c"
    w.stop()


def test_locks_exclude_across_clients(server, client):
    other = EtcdBackend(port=server.port, lease_ttl=5.0)
    try:
        lock = client.lock_path("locks/x", timeout=5)
        with pytest.raises(KVLockError):
            other.lock_path("locks/x", timeout=0.4)
        lock.unlock()
        other.lock_path("locks/x", timeout=5).unlock()
    finally:
        other.close()


def test_lock_released_when_holder_dies(server):
    holder = EtcdBackend(port=server.port, lease_ttl=1.0)
    waiter = EtcdBackend(port=server.port, lease_ttl=30.0)
    try:
        holder.lock_path("locks/y", timeout=5)
        holder._closed.set()  # keepalive dies; lease lapses
        lock = waiter.lock_path("locks/y", timeout=10)
        lock.unlock()
    finally:
        waiter.close()
        holder.close()


# -------------------------------------------------------- allocator tier

def test_identity_allocation_converges_across_etcd_clients(server):
    from cilium_tpu.kvstore.identity_allocator import \
        DistributedIdentityAllocator
    from cilium_tpu.labels import Labels
    a = EtcdBackend(port=server.port, lease_ttl=5.0)
    b = EtcdBackend(port=server.port, lease_ttl=5.0)
    try:
        da = DistributedIdentityAllocator(a, "node-a")
        db = DistributedIdentityAllocator(b, "node-b")
        labels = Labels.from_model(["k8s:app=web"])
        ia, _ = da.allocate(labels)
        ib, _ = db.allocate(labels)
        assert ia.id == ib.id, \
            "same labels must resolve to one identity across the wire"
        other, _ = db.allocate(Labels.from_model(["k8s:app=db"]))
        assert other.id != ia.id
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------- failure tier

def _spawn_agent(tmp_path, port, node, mode, ttl=2.0):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    errfile = open(tmp_path / f"{node}.stderr", "w+")
    proc = subprocess.Popen(
        [sys.executable, AGENT, str(port), node, mode, str(ttl),
         "etcd"],
        stdout=subprocess.PIPE, stderr=errfile, text=True, env=env)
    proc._errfile = errfile
    return proc


def _read_report(proc, timeout=90):
    out = {}

    def read():
        out["line"] = proc.stdout.readline()

    t = threading.Thread(target=read, daemon=True)
    t.start()
    t.join(timeout)
    line = out.get("line")
    if not line:
        proc.kill()
        proc._errfile.seek(0)
        raise AssertionError(
            f"agent produced no report; stderr:\n"
            f"{proc._errfile.read()[-2000:]}")
    import json
    return json.loads(line)


def test_kill9_agent_lease_reaped_on_etcd(server, tmp_path):
    """The VERDICT #6 'done' criterion: identity-allocation kill -9
    reclamation green on the etcd-protocol backend."""
    victim = _spawn_agent(tmp_path, server.port, "node-a", "sleep",
                          ttl=1.0)
    observer = EtcdBackend(port=server.port, lease_ttl=30.0)
    try:
        _read_report(victim)
        ident_prefix = "cilium/state/identities/v1/"
        slaves = observer.list_prefix(ident_prefix + "value/")
        assert slaves, "agent should hold lease-backed slave keys"
        os.kill(victim.pid, signal.SIGKILL)
        victim.wait(timeout=10)
        deadline = time.time() + 10
        while time.time() < deadline:
            if not observer.list_prefix(ident_prefix + "value/"):
                break
            time.sleep(0.2)
        assert observer.list_prefix(ident_prefix + "value/") == {}, \
            "slave keys must vanish after the dead agent's TTL"
        masters = observer.list_prefix(ident_prefix + "id/")
        assert masters
        from cilium_tpu.kvstore.allocator import Allocator
        gc_alloc = Allocator(observer, "cilium/state/identities/v1",
                             node="gc-node", min_id=256, max_id=65535)
        reclaimed = gc_alloc.run_gc()
        assert reclaimed == len(masters)
        assert observer.list_prefix(ident_prefix + "id/") == {}
        gc_alloc.close()
    finally:
        observer.close()
        if victim.poll() is None:
            victim.kill()
