"""Socket-level L7 proxy data plane: real TCP through the policy path.

Round-1 gap closed: redirects were in-process engine calls on already-
parsed requests.  These tests run live connections through the proxy:

- memcached via the generic parser framework (deny frames injected
  in-protocol, upstream never sees denied requests);
- kafka via the dedicated handler (typed error responses with matching
  correlation ids; the correlation cache attributes responses and logs
  latency — pkg/kafka/correlation_cache.go:97);
- http/1.1 framing + 403 deny;
- the full chain: packet batch -> datapath verdict = proxy_port ->
  real TCP connect through that port -> denied in-protocol.
"""

import socket
import socketserver
import struct
import threading
import time

import numpy as np
import pytest

from cilium_tpu.l7.socket_proxy import (CorrelationCache, ListenerContext,
                                        SocketProxy,
                                        TOPIC_AUTHORIZATION_FAILED,
                                        kafka_deny_response)
from cilium_tpu.l7.kafka import KafkaPolicyEngine, parse_kafka_request
from cilium_tpu.l7.http import HTTPPolicyEngine
from cilium_tpu.l7.parser import PortRuleL7
from cilium_tpu.policy.api import PortRuleHTTP, PortRuleKafka
from cilium_tpu.proxy import AccessLog


class _Upstream(socketserver.ThreadingTCPServer):
    """Records everything it receives; replies per handler_fn."""

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, handler_fn):
        self.received = []
        self.handler_fn = handler_fn
        super().__init__(("127.0.0.1", 0), _UpHandler)
        threading.Thread(target=self.serve_forever, daemon=True).start()

    @property
    def port(self):
        return self.server_address[1]


class _UpHandler(socketserver.BaseRequestHandler):
    def handle(self):
        while True:
            try:
                data = self.request.recv(65536)
            except OSError:
                return
            if not data:
                return
            self.server.received.append(data)
            reply = self.server.handler_fn(data)
            if reply:
                self.request.sendall(reply)


def _connect(port):
    s = socket.create_connection(("127.0.0.1", port), timeout=5)
    s.settimeout(5)
    return s


def _recv_until(sock, token, timeout=5):
    deadline = time.time() + timeout
    buf = b""
    while token not in buf and time.time() < deadline:
        try:
            chunk = sock.recv(65536)
        except socket.timeout:
            break
        if not chunk:
            break
        buf += chunk
    return buf


@pytest.fixture()
def proxy():
    log = AccessLog()
    sp = SocketProxy(access_log=log)
    sp.test_log = log
    yield sp
    sp.shutdown()


# ----------------------------------------------------- generic (memcached)

def test_memcached_stream_through_proxy(proxy):
    upstream = _Upstream(lambda data: b"END\r\n")
    ctx = ListenerContext(
        redirect_id="1:ingress:TCP:11211", parser_type="memcache",
        orig_dst=lambda peer: ("127.0.0.1", upstream.port),
        l7_rules=lambda peer: [PortRuleL7.from_dict(
            {"command": "get", "key": "sess:*"})],
        identities=lambda peer: (101, 202))
    port = proxy.start_listener(0, ctx)
    c = _connect(port)
    try:
        # allowed request reaches the upstream; reply flows back
        c.sendall(b"get sess:42\r\n")
        assert b"END\r\n" in _recv_until(c, b"END\r\n")
        assert b"get sess:42\r\n" in b"".join(upstream.received)
        # denied request: SERVER_ERROR injected in-protocol, upstream
        # never sees it
        c.sendall(b"get secret:1\r\n")
        assert b"SERVER_ERROR" in _recv_until(c, b"\r\n")
        assert b"secret" not in b"".join(upstream.received)
    finally:
        c.close()
        upstream.shutdown()
    verdicts = [e.verdict for e in proxy.test_log.tail()]
    assert "forwarded" in verdicts and "denied" in verdicts
    src_ids = {e.src_identity for e in proxy.test_log.tail()}
    assert 101 in src_ids


# -------------------------------------------------------------- kafka

def _kafka_request(api_key, corr, topic, client=b"cli"):
    # header: api_key, api_version=0, correlation, client_id
    body = struct.pack(">hhi", api_key, 0, corr)
    body += struct.pack(">h", len(client)) + client
    if api_key == 0:  # produce v0: acks, timeout, topics
        body += struct.pack(">hi", 1, 1000)
        body += struct.pack(">i", 1)
        body += struct.pack(">h", len(topic)) + topic
        body += struct.pack(">i", 0)  # partitions: []
    return struct.pack(">i", len(body)) + body


def test_kafka_acl_and_correlation(proxy):
    def broker(data):
        # echo a response frame per request frame: size + corr + int16
        out = b""
        while len(data) >= 4:
            (size,) = struct.unpack_from(">i", data, 0)
            frame = data[:4 + size]
            (corr,) = struct.unpack_from(">i", frame, 8)
            payload = struct.pack(">ih", corr, 0)
            out += struct.pack(">i", len(payload)) + payload
            data = data[4 + size:]
        return out

    upstream = _Upstream(broker)
    engine = KafkaPolicyEngine([PortRuleKafka(api_key="produce",
                                              topic="allowed-topic")])
    ctx = ListenerContext(
        redirect_id="2:egress:TCP:9092", parser_type="kafka",
        orig_dst=lambda peer: ("127.0.0.1", upstream.port),
        kafka_engine_for=lambda peer: engine)
    port = proxy.start_listener(0, ctx)
    c = _connect(port)
    try:
        # allowed produce: forwarded; broker response correlated back
        c.sendall(_kafka_request(0, 7, b"allowed-topic"))
        resp = _recv_until(c, struct.pack(">i", 7))
        assert len(resp) >= 8
        (corr,) = struct.unpack_from(">i", resp, 4)
        assert corr == 7
        # denied produce: typed error response, correct correlation id,
        # error code 29; never forwarded
        before = len(b"".join(upstream.received))
        c.sendall(_kafka_request(0, 9, b"forbidden-topic"))
        resp = _recv_until(c, struct.pack(">i", 9))
        (size,) = struct.unpack_from(">i", resp, 0)
        (corr,) = struct.unpack_from(">i", resp, 4)
        assert corr == 9
        assert struct.pack(">h", TOPIC_AUTHORIZATION_FAILED) in resp
        assert b"forbidden-topic" not in b"".join(
            upstream.received)[before:]
    finally:
        c.close()
        upstream.shutdown()
    entries = proxy.test_log.tail()
    verdicts = [e.verdict for e in entries]
    assert "forwarded" in verdicts and "denied" in verdicts
    responses = [e for e in entries if e.verdict == "response"]
    assert responses and responses[0].info["correlation_id"] == 7
    assert "latency_ms" in responses[0].info


def test_kafka_deny_response_shapes():
    for api_key in (0, 1, 3, 10):
        req = parse_kafka_request(_kafka_request(0, 42, b"t"))
        req.api_key = api_key
        frame = kafka_deny_response(req)
        (size,) = struct.unpack_from(">i", frame, 0)
        assert len(frame) == 4 + size
        (corr,) = struct.unpack_from(">i", frame, 4)
        assert corr == 42


def test_correlation_cache_capacity():
    cache = CorrelationCache(capacity=2)
    for i in range(4):
        req = parse_kafka_request(_kafka_request(0, i, b"t"))
        cache.put(req)
    assert len(cache) == 2 and cache.overflows == 2
    assert cache.correlate(3) is not None
    assert cache.correlate(0) is None  # evicted


# ---------------------------------------------------------------- http

def test_http_allow_deny_through_proxy(proxy):
    ok_response = (b"HTTP/1.1 200 OK\r\ncontent-length: 2\r\n\r\nhi")
    upstream = _Upstream(lambda data: ok_response)
    engine = HTTPPolicyEngine([PortRuleHTTP(method="GET",
                                            path="/public/.*")])
    ctx = ListenerContext(
        redirect_id="3:ingress:TCP:80", parser_type="http",
        orig_dst=lambda peer: ("127.0.0.1", upstream.port),
        http_engine_for=lambda peer: engine)
    port = proxy.start_listener(0, ctx)
    c = _connect(port)
    try:
        c.sendall(b"GET /public/index.html HTTP/1.1\r\n"
                  b"Host: site\r\ncontent-length: 0\r\n\r\n")
        assert b"200 OK" in _recv_until(c, b"hi")
    finally:
        c.close()
    c = _connect(port)
    try:
        before = len(b"".join(upstream.received))
        c.sendall(b"POST /admin HTTP/1.1\r\nHost: site\r\n"
                  b"content-length: 0\r\n\r\n")
        resp = _recv_until(c, b"denied")
        assert b"403" in resp
        assert b"/admin" not in b"".join(upstream.received)[before:]
    finally:
        c.close()
        upstream.shutdown()


def test_http_batched_verdicts_through_proxy():
    """The live-proxy batch path: with http_batch_window set,
    concurrent requests from many connections are micro-batched
    through the engine (parser.VerdictBatcher) and must produce the
    same allow/deny verdicts as the scalar path."""
    ok_response = (b"HTTP/1.1 200 OK\r\ncontent-length: 2\r\n\r\nhi")
    upstream = _Upstream(lambda data: ok_response)
    engine = HTTPPolicyEngine([PortRuleHTTP(method="GET",
                                            path="/public/.*")])
    log = AccessLog()
    sp = SocketProxy(access_log=log, http_batch_window=0.002)
    try:
        ctx = ListenerContext(
            redirect_id="3b:ingress:TCP:80", parser_type="http",
            orig_dst=lambda peer: ("127.0.0.1", upstream.port),
            http_engine_for=lambda peer: engine)
        port = sp.start_listener(0, ctx)
        results = {}

        def one(i):
            allowed = i % 2 == 0
            path = f"/public/{i}" if allowed else f"/admin/{i}"
            c = _connect(port)
            try:
                c.sendall(f"GET {path} HTTP/1.1\r\nHost: s\r\n"
                          f"content-length: 0\r\n\r\n".encode())
                resp = _recv_until(c, b"hi" if allowed else b"denied")
                results[i] = b"200 OK" in resp if allowed \
                    else b"403" in resp
            finally:
                c.close()

        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        assert len(results) == 12 and all(results.values()), results
        # the batcher actually saw traffic (and ideally coalesced some)
        _eng, batcher = sp._http_batchers[id(engine)]
        assert batcher.checked == 12
        assert batcher.errors == 0
    finally:
        sp.shutdown()
        upstream.shutdown()


# ------------------------------------------------ full verdict -> socket

def test_packet_verdict_to_socket_e2e(proxy):
    """BASELINE's slow-path contract: the datapath's proxy_port verdict
    IS the TCP port the proxied connection traverses."""
    from cilium_tpu.compiler.policy_tables import compile_endpoints
    from cilium_tpu.datapath.verdict import VerdictEngine, make_packet_batch
    from cilium_tpu.policy.mapstate import (INGRESS, PolicyKey,
                                            PolicyMapState,
                                            PolicyMapStateEntry)
    upstream = _Upstream(lambda data: b"END\r\n")
    # redirect port allocated in the proxy range, used as the verdict
    proxy_port = 10007
    st = PolicyMapState()
    st[PolicyKey(identity=301, dest_port=11211, nexthdr=6,
                 direction=INGRESS)] = \
        PolicyMapStateEntry(proxy_port=proxy_port)
    eng = VerdictEngine(compile_endpoints([st], revision=1))
    batch = make_packet_batch(endpoint=[0], identity=[301],
                              dport=[11211], proto=[6], direction=[0],
                              length=[64])
    verdict = int(np.asarray(eng(batch))[0])
    assert verdict == proxy_port
    # the datapath says "redirect to proxy_port"; bind it and connect
    ctx = ListenerContext(
        redirect_id="7:ingress:TCP:11211", parser_type="memcache",
        orig_dst=lambda peer: ("127.0.0.1", upstream.port),
        l7_rules=lambda peer: [PortRuleL7.from_dict(
            {"command": "get", "key": "ok*"})])
    bound = proxy.start_listener(verdict, ctx)
    assert bound == proxy_port
    c = _connect(verdict)
    try:
        c.sendall(b"get secret\r\n")
        assert b"SERVER_ERROR" in _recv_until(c, b"\r\n")
        c.sendall(b"get ok:1\r\n")
        assert b"END\r\n" in _recv_until(c, b"END\r\n")
    finally:
        c.close()
        upstream.shutdown()


# ------------------------------------------- ProxyManager integration

def test_proxy_manager_activate_redirect():
    """Redirect lifecycle drives the data plane: create -> activate
    (listener on the allocated port, engines resolved per remote
    labels) -> remove (listener gone)."""
    from cilium_tpu.policy.api import L7Rules
    from cilium_tpu.policy.l4 import (L4Filter, L7DataMap,
                                      PARSER_TYPE_HTTP,
                                      WILDCARD_SELECTOR)
    from cilium_tpu.proxy import ProxyManager

    upstream = _Upstream(
        lambda data: b"HTTP/1.1 200 OK\r\ncontent-length: 2\r\n\r\nok")
    l7map = L7DataMap()
    l7map[WILDCARD_SELECTOR] = L7Rules(
        http=[PortRuleHTTP(method="GET", path="/api/.*")])
    flt = L4Filter(port=8080, protocol="TCP", u8proto=6,
                   l7_parser=PARSER_TYPE_HTTP, l7_rules_per_ep=l7map,
                   ingress=True)
    pm = ProxyManager()
    try:
        redir = pm.create_or_update_redirect(flt, endpoint_id=5)
        bound = pm.activate_redirect(
            redir, orig_dst=lambda peer: ("127.0.0.1", upstream.port))
        assert bound == redir.proxy_port
        c = _connect(bound)
        try:
            c.sendall(b"GET /api/x HTTP/1.1\r\nHost: h\r\n"
                      b"content-length: 0\r\n\r\n")
            assert b"200 OK" in _recv_until(c, b"ok")
        finally:
            c.close()
        c = _connect(bound)
        try:
            c.sendall(b"GET /other HTTP/1.1\r\nHost: h\r\n"
                      b"content-length: 0\r\n\r\n")
            assert b"403" in _recv_until(c, b"denied")
        finally:
            c.close()
        # removal tears the listener down
        assert pm.remove_redirect(redir.id)
        with pytest.raises(OSError):
            _connect(bound)
        assert any(e.verdict == "denied" for e in pm.access_log.tail())
    finally:
        pm.shutdown_dataplane()
        upstream.shutdown()
