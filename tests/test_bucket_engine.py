"""Two-choice bucketed verdict engine: build + lookup + oracle parity.

Mirrors the hash-engine parity tests; the bucket layout is the at-scale
policymap analog (policymap.go:37's 16,384-entry maps), so parity with
the scalar oracle (bpf/lib/policy.h __policy_can_access) is the gate.
"""

import numpy as np
import pytest

from cilium_tpu.compiler.bucket_tables import (build_bucket_tables,
                                               compile_states_bucketed)
from cilium_tpu.compiler.policy_tables import oracle_verdict, pack_key
from cilium_tpu.ops.bucket_ops import BucketVerdictEngine
from cilium_tpu.policy.mapstate import (EGRESS, INGRESS, PolicyKey,
                                        PolicyMapState, PolicyMapStateEntry)


def random_states(n_endpoints=20, per_ep=60, seed=0):
    rng = np.random.default_rng(seed)
    states = []
    for _ in range(n_endpoints):
        st = PolicyMapState()
        idents = rng.choice(np.arange(256, 5000), per_ep, replace=False)
        for ident in idents:
            kind = rng.integers(0, 3)
            if kind == 0:  # exact
                st[PolicyKey(identity=int(ident),
                             dest_port=int(rng.integers(1, 65536)),
                             nexthdr=6,
                             direction=int(rng.integers(0, 2)))] = \
                    PolicyMapStateEntry(
                        proxy_port=int(rng.choice([0, 0, 15001])))
            elif kind == 1:  # L3-only
                st[PolicyKey(identity=int(ident),
                             direction=int(rng.integers(0, 2)))] = \
                    PolicyMapStateEntry()
            else:  # L4 wildcard
                st[PolicyKey(identity=0,
                             dest_port=int(rng.integers(1, 65536)),
                             nexthdr=6,
                             direction=int(rng.integers(0, 2)))] = \
                    PolicyMapStateEntry()
        states.append(st)
    return states


def test_build_places_every_entry():
    states = random_states()
    tables = compile_states_bucketed(states)
    want = sum(len(st) for st in states)
    assert tables.entry_count() == want
    # load bound respected: slots ~ 2x entries per endpoint
    assert tables.slots_per_ep >= 2 * max(len(st) for st in states) - 1


def test_build_deterministic():
    states = random_states(seed=3)
    a = compile_states_bucketed(states)
    b = compile_states_bucketed(states)
    assert np.array_equal(a.key_a, b.key_a)
    assert np.array_equal(a.key_b, b.key_b)
    assert np.array_equal(a.value, b.value)


def test_rejects_zero_meta_key():
    with pytest.raises(ValueError):
        build_bucket_tables(np.array([0]), np.array([1], np.uint32),
                            np.array([0], np.uint32),
                            np.array([0], np.int32), num_endpoints=1)


def test_oracle_parity_random_traffic():
    states = random_states(n_endpoints=16, per_ep=80, seed=7)
    eng = BucketVerdictEngine(compile_states_bucketed(states, revision=4))
    assert eng.revision == 4
    rng = np.random.default_rng(11)
    b = 4096
    ep = rng.integers(0, len(states), b).astype(np.int32)
    ident = rng.integers(0, 5200, b).astype(np.int32)
    dport = rng.integers(1, 65536, b).astype(np.int32)
    proto = np.full(b, 6, np.int32)
    direction = rng.integers(0, 2, b).astype(np.int32)
    length = np.full(b, 100, np.int32)
    got = np.asarray(eng(ep, ident, dport, proto, direction, length))
    for i in range(b):
        want = oracle_verdict(states[ep[i]], int(ident[i]), int(dport[i]),
                              6, int(direction[i]))
        assert got[i] == want, (i, got[i], want)


def test_oracle_parity_targeted_traffic():
    """Random traffic rarely hits; also steer at known keys so every
    stage (exact / L3-only / L4-wildcard / proxy redirect) is hit."""
    states = random_states(n_endpoints=8, per_ep=50, seed=5)
    eng = BucketVerdictEngine(compile_states_bucketed(states))
    eps, idents, dports, dirs = [], [], [], []
    for e, st in enumerate(states):
        for k in list(st)[:20]:
            eps.append(e)
            idents.append(k.identity if k.identity else 999)
            dports.append(k.dest_port if k.dest_port else 80)
            dirs.append(k.direction)
    b = len(eps)
    got = np.asarray(eng(np.array(eps), np.array(idents),
                         np.array(dports), np.full(b, 6),
                         np.array(dirs), np.full(b, 64)))
    hits = 0
    for i in range(b):
        want = oracle_verdict(states[eps[i]], idents[i], dports[i], 6,
                              dirs[i])
        assert got[i] == want
        if want >= 0:
            hits += 1
    assert hits > b // 4  # targeted traffic must actually hit


def test_fragment_semantics():
    st = PolicyMapState()
    st[PolicyKey(identity=300, dest_port=80, nexthdr=6,
                 direction=INGRESS)] = PolicyMapStateEntry()
    st[PolicyKey(identity=400, direction=INGRESS)] = PolicyMapStateEntry()
    eng = BucketVerdictEngine(compile_states_bucketed([st]))
    got = np.asarray(eng(
        pkt_ep=[0, 0], pkt_ident=[300, 400], pkt_dport=[80, 80],
        pkt_proto=[6, 6], pkt_dir=[0, 0], pkt_len=[64, 64],
        pkt_frag=[1, 1]))
    # L4 match unusable on fragments -> frag drop; L3-only still allows
    assert got[0] == -2 and got[1] == 0


def test_counters_accumulate():
    st = PolicyMapState()
    st[PolicyKey(identity=300, dest_port=80, nexthdr=6,
                 direction=INGRESS)] = PolicyMapStateEntry()
    eng = BucketVerdictEngine(compile_states_bucketed([st]))
    for _ in range(3):
        eng(pkt_ep=[0, 0], pkt_ident=[300, 999], pkt_dport=[80, 80],
            pkt_proto=[6, 6], pkt_dir=[0, 0], pkt_len=[100, 100])
    assert int(np.asarray(eng.counters.packets).sum()) == 3
    assert int(np.asarray(eng.counters.bytes).sum()) == 300


def test_vectorized_build_matches_flat_arrays_at_scale():
    """Mid-scale smoke of the flat-array build path the benchmark uses
    (bypassing PolicyMapState objects)."""
    rng = np.random.default_rng(2)
    E, per = 200, 300
    ident = rng.integers(256, 1 << 20, (E, per)).astype(np.uint32)
    meta = (((rng.integers(1, 65536, (E, per))) << 16) | (6 << 8) |
            1).astype(np.uint32)
    ep = np.repeat(np.arange(E, dtype=np.int64), per)
    tables = build_bucket_tables(ep, ident.ravel(), meta.ravel(),
                                 np.zeros(E * per, np.int32),
                                 num_endpoints=E)
    assert tables.entry_count() == E * per
    eng = BucketVerdictEngine(tables)
    # every inserted key must be found (verdict 0), payload correct
    sel = rng.integers(0, E * per, 2048)
    got = np.asarray(eng(ep[sel], ident.ravel()[sel].view(np.int32),
                         (meta.ravel()[sel] >> 16).astype(np.int32),
                         np.full(2048, 6), np.zeros(2048, np.int32),
                         np.full(2048, 64)))
    assert (got == 0).all()


def test_tiny_table_no_double_count():
    """nb must never be 1: both bucket choices would alias the same row
    and a proxy-port hit would be summed twice (15001 -> 30002)."""
    st = PolicyMapState()
    st[PolicyKey(identity=777, dest_port=443, nexthdr=6,
                 direction=INGRESS)] = \
        PolicyMapStateEntry(proxy_port=15001)
    st[PolicyKey(identity=888, direction=INGRESS)] = PolicyMapStateEntry()
    tables = compile_states_bucketed([st])
    assert tables.buckets_per_ep >= 2
    eng = BucketVerdictEngine(tables)
    got = np.asarray(eng([0, 0, 0], [777, 888, 999], [443] * 3, [6] * 3,
                         [0] * 3, [64] * 3))
    assert list(got) == [15001, 0, -1]
