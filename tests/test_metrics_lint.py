"""Metric-surface lint: documented by construction.

A static pass over the process metrics registry (every module that
registers series is imported first) that fails when:

- any metric is registered without help text, or
- any registered metric is missing from the README's
  "Metric inventory" table, or
- the README inventory names a metric that no longer exists (stale
  docs are as misleading as missing ones).

This keeps the /metrics surface and its documentation in lockstep —
adding a series without documenting it is a test failure, not a
review nit.
"""

import os
import re

# import every module that registers metrics (the registry is
# process-global; registration happens at import time)
import cilium_tpu.utils.metrics as metrics_mod
import cilium_tpu.utils.resilience  # noqa: F401
import cilium_tpu.observability  # noqa: F401
import cilium_tpu.datapath.serving  # noqa: F401

README = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "README.md")


def _registered():
    with metrics_mod.registry._lock:
        return dict(metrics_mod.registry._metrics)


def _readme_inventory():
    """Metric names from the README inventory table (first backticked
    column of rows inside the 'Metric inventory' section)."""
    with open(README) as f:
        text = f.read()
    section = text.split("### Metric inventory", 1)
    assert len(section) == 2, "README lost its Metric inventory section"
    names = set()
    for line in section[1].splitlines():
        m = re.match(r"\|\s*`(cilium_tpu_[a-z0-9_]+)`\s*\|", line)
        if m:
            names.add(m.group(1))
        elif line.startswith("## "):
            break  # next top-level section
    assert names, "Metric inventory table is empty"
    return names


def test_every_metric_has_help_text():
    missing = [name for name, m in _registered().items() if not m.help]
    assert not missing, \
        f"metrics registered without help text: {sorted(missing)}"


def test_every_metric_documented_in_readme():
    documented = _readme_inventory()
    undocumented = sorted(set(_registered()) - documented)
    assert not undocumented, (
        "metrics missing from the README 'Metric inventory' table "
        f"(add a row per metric): {undocumented}")


def test_readme_inventory_is_not_stale():
    documented = _readme_inventory()
    stale = sorted(documented - set(_registered()))
    assert not stale, (
        "README 'Metric inventory' documents metrics that are no "
        f"longer registered: {stale}")


def test_registry_names_are_prometheus_legal():
    bad = [n for n in _registered()
           if not re.fullmatch(r"[a-zA-Z_:][a-zA-Z0-9_:]*", n)]
    assert not bad, f"illegal metric names: {bad}"
