"""The proxy-plane process boundary: xDS over TCP + supervised child.

Reference parity:
  * pkg/envoy/server.go:114 — xDS streams with versioned resources and
    ACKs; policy pushes block on client ACK (AckingResourceMutator);
  * pkg/envoy/envoy.go:145 — Envoy runs as a supervised child process,
    restarted on death;
  * the apply-then-ack contract: the push barrier completing means the
    out-of-process proxy is actually enforcing the new policy.

The e2e test is the VERDICT cycle: kill -9 the proxy -> supervisor
restarts it -> it re-syncs from the cache -> a policy push completes
and the NEW rules are enforced on live TCP.
"""

import os
import signal
import socket
import socketserver
import threading
import time

import pytest

from cilium_tpu.l7.supervisor import ProxySupervisor
from cilium_tpu.l7.xds_wire import XDSWireClient, XDSWireServer
from cilium_tpu.xds import Cache, TYPE_NETWORK_POLICY


class _Upstream(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self):
        self.received = []
        super().__init__(("127.0.0.1", 0), _UpHandler)
        threading.Thread(target=self.serve_forever, daemon=True).start()

    @property
    def port(self):
        return self.server_address[1]


class _UpHandler(socketserver.BaseRequestHandler):
    def handle(self):
        while True:
            try:
                data = self.request.recv(65536)
            except OSError:
                return
            if not data:
                return
            self.server.received.append(data)
            self.request.sendall(
                b"HTTP/1.1 200 OK\r\ncontent-length: 2\r\n\r\nok")


def _http_get(port, path, timeout=5):
    s = socket.create_connection(("127.0.0.1", port), timeout=timeout)
    s.settimeout(timeout)
    try:
        s.sendall(f"GET {path} HTTP/1.1\r\nHost: h\r\n"
                  f"Content-Length: 0\r\n\r\n".encode())
        buf = b""
        while True:
            try:
                chunk = s.recv(65536)
            except (socket.timeout, OSError):
                break
            if not chunk:
                break
            buf += chunk
            if b"ok" in buf or b"denied" in buf:
                break
        return buf
    finally:
        s.close()


def _npds(upstream_port, proxy_port, path_re):
    return {"1": {"name": "1", "policy": 1, "proxy_port": proxy_port,
                  "upstream": ["127.0.0.1", upstream_port],
                  "http_rules": [{"method": "GET", "path": path_re}]}}


def _wait(pred, timeout=20.0, step=0.1):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(step)
    return False


# ----------------------------------------------------- wire-level unit

def test_xds_wire_push_ack_barrier():
    """In-process client over real TCP: push -> apply -> ack completes
    the agent-side barrier."""
    cache = Cache()
    server = XDSWireServer(cache).start()
    applied = []

    client = XDSWireClient(server.port, client="c1")
    client.subscribe(TYPE_NETWORK_POLICY,
                     lambda v, res: (applied.append((v, res)), True)[1])
    time.sleep(0.2)  # subscription registered server-side

    v = cache.set_resources(TYPE_NETWORK_POLICY, {"1": {"policy": 7}})
    comp = cache.wait_for_acks(TYPE_NETWORK_POLICY, v)
    assert comp.wait(5), "push barrier never completed"
    assert applied and applied[-1][0] == v
    assert applied[-1][1]["1"]["policy"] == 7
    client.close()
    server.shutdown()


def test_xds_wire_nack_recorded():
    cache = Cache()
    server = XDSWireServer(cache).start()
    client = XDSWireClient(server.port, client="bad")
    client.subscribe(TYPE_NETWORK_POLICY,
                     lambda v, res: (_ for _ in ()).throw(
                         ValueError("cannot apply")))
    time.sleep(0.2)
    v = cache.set_resources(TYPE_NETWORK_POLICY, {"1": {}})
    assert _wait(lambda: any(n[2] == v for n in cache.nacks))
    client.close()
    server.shutdown()


# --------------------------------------------------- supervised child

def test_supervised_proxy_kill9_restart_resync_push():
    """The full VERDICT cycle across a real process boundary."""
    cache = Cache()
    server = XDSWireServer(cache).start()
    upstream = _Upstream()
    # ephemeral port reserved then released: no interference from a
    # stale child of a previous (failed) run
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    proxy_port = probe.getsockname()[1]
    probe.close()

    # v1 policy BEFORE the child exists: allow only /public/.*
    v1 = cache.set_resources(
        TYPE_NETWORK_POLICY,
        _npds(upstream.port, proxy_port, "/public/.*"))

    sup = ProxySupervisor(server.port, backoff_base=0.1).start()
    try:
        # the child subscribed, applied v1 (ACK barrier spans the
        # process boundary), and enforces it on live TCP
        assert cache.wait_for_acks(TYPE_NETWORK_POLICY, v1).wait(15)
        assert b"200 OK" in _http_get(proxy_port, "/public/a")
        assert b"403" in _http_get(proxy_port, "/admin")

        # kill -9 the proxy process
        pid = sup.pid
        os.kill(pid, signal.SIGKILL)
        assert _wait(lambda: sup.pid is not None and sup.pid != pid
                     and sup.alive(), 20), "supervisor never restarted"
        assert sup.restarts >= 1

        # the restarted child re-synced the CURRENT version from the
        # cache and enforces it again
        assert _wait(lambda: b"200 OK" in _http_get(proxy_port,
                                                    "/public/b"), 15)

        # a NEW policy push completes against the restarted child and
        # the new rules take effect (allow /api, deny /public)
        v2 = cache.set_resources(
            TYPE_NETWORK_POLICY,
            _npds(upstream.port, proxy_port, "/api/.*"))
        assert cache.wait_for_acks(TYPE_NETWORK_POLICY, v2).wait(15)
        assert b"200 OK" in _http_get(proxy_port, "/api/x")
        assert b"403" in _http_get(proxy_port, "/public/a")
    finally:
        sup.shutdown()
        server.shutdown()
        upstream.shutdown()


# ------------------------------------------------- daemon integration

def test_daemon_serves_xds_to_child_proxy():
    """The agent side: Daemon.serve_xds publishes proxy redirects as
    NPDS resources and ip->identity as NPHDS; a wire client (standing
    in for the child) receives both and its ACK completes the barrier."""
    from cilium_tpu.daemon import Daemon
    from cilium_tpu.policy.api import L7Rules, PortRuleHTTP
    from cilium_tpu.policy.l4 import (L4Filter, L7DataMap,
                                      PARSER_TYPE_HTTP,
                                      WILDCARD_SELECTOR)
    from cilium_tpu.utils.option import DaemonConfig
    from cilium_tpu.xds import TYPE_NETWORK_POLICY_HOSTS

    d = Daemon(config=DaemonConfig())
    server = d.serve_xds()
    d.endpoint_create(1, ipv4="10.77.0.2", labels=["k8s:app=xdsweb"])

    l7map = L7DataMap()
    l7map[WILDCARD_SELECTOR] = L7Rules(
        http=[PortRuleHTTP(method="GET", path="/v1/.*")])
    flt = L4Filter(port=8080, protocol="TCP", u8proto=6,
                   l7_parser=PARSER_TYPE_HTTP, l7_rules_per_ep=l7map,
                   ingress=True)
    redir = d.proxy.create_or_update_redirect(flt, endpoint_id=1)

    got = {}

    def apply_npds(v, res):
        got.clear()
        got.update(res)  # full-set replacement, like the child
        return True

    client = XDSWireClient(server.port, client="test-proxy")
    client.subscribe(TYPE_NETWORK_POLICY, apply_npds)
    hosts = {}
    client.subscribe(TYPE_NETWORK_POLICY_HOSTS,
                     lambda v, res: (hosts.update(res), True)[1])

    assert _wait(lambda: redir.id in got), got
    res = got[redir.id]
    assert res["proxy_port"] == redir.proxy_port
    assert res["http_rules"] == [{"method": "GET", "path": "/v1/.*",
                                  "host": ""}]
    # NPHDS carries the endpoint's ip under its identity
    assert _wait(lambda: any("10.77.0.2/32" in h["host_addresses"]
                             for h in hosts.values())), hosts

    # a fresh push blocks on this client's ACK across the wire
    d.proxy.remove_redirect(redir.id)
    v = d.xds_cache._version_of(TYPE_NETWORK_POLICY)
    assert d.xds_cache.wait_for_acks(TYPE_NETWORK_POLICY, v).wait(10)
    assert _wait(lambda: redir.id not in got)
    client.close()
    d.shutdown()


# ------------------------------------------- hostile-client behavior
# (pkg/envoy/xds/server_e2e_test.go: slow clients, NACKs, stream
#  disconnects must not wedge the agent's push barriers)

def test_slow_client_holds_barrier_until_it_acks():
    """All-watchers semantics: one fast ACKer is not enough while a
    slow client hasn't applied yet."""
    cache = Cache()
    server = XDSWireServer(cache).start()
    fast = XDSWireClient(server.port, client="fast")
    fast.subscribe(TYPE_NETWORK_POLICY, lambda v, res: True)
    gate = threading.Event()
    slow = XDSWireClient(server.port, client="slow")
    slow.subscribe(TYPE_NETWORK_POLICY,
                   lambda v, res: gate.wait(30) or True)

    v = cache.set_resources(TYPE_NETWORK_POLICY, {"1": {}})
    comp = cache.wait_for_acks(TYPE_NETWORK_POLICY, v)
    assert not comp.wait(0.8), "barrier completed without the slow ACK"
    gate.set()  # slow client finally applies
    assert comp.wait(10)
    fast.close()
    slow.close()
    server.shutdown()


def test_nacking_client_does_not_block_other_subscribers():
    cache = Cache()
    server = XDSWireServer(cache).start()
    good_versions = []
    good = XDSWireClient(server.port, client="good")
    good.subscribe(TYPE_NETWORK_POLICY,
                   lambda v, res: (good_versions.append(v), True)[1])
    bad = XDSWireClient(server.port, client="bad")
    bad.subscribe(TYPE_NETWORK_POLICY, lambda v, res: False)  # NACKs

    v = cache.set_resources(TYPE_NETWORK_POLICY, {"1": {}})
    assert _wait(lambda: v in good_versions)
    assert _wait(lambda: any(n[1] == "bad" and n[2] == v
                             for n in cache.nacks))
    # the good client keeps receiving subsequent versions
    v2 = cache.set_resources(TYPE_NETWORK_POLICY, {"1": {}, "2": {}})
    assert _wait(lambda: v2 in good_versions)
    good.close()
    bad.close()
    server.shutdown()


def test_client_disconnect_mid_barrier_unblocks_push():
    """A proxy that dies while a push waits on its ACK must not wedge
    the agent: the barrier completes on the surviving watcher set."""
    cache = Cache()
    server = XDSWireServer(cache).start()
    fast = XDSWireClient(server.port, client="fast")
    fast.subscribe(TYPE_NETWORK_POLICY, lambda v, res: True)
    dead = XDSWireClient(server.port, client="doomed")
    dead.subscribe(TYPE_NETWORK_POLICY,
                   lambda v, res: time.sleep(60) or True)  # never acks

    v = cache.set_resources(TYPE_NETWORK_POLICY, {"1": {}})
    comp = cache.wait_for_acks(TYPE_NETWORK_POLICY, v)
    assert not comp.wait(0.5)
    dead.close()  # kill -9 analog: the connection drops mid-barrier
    assert comp.wait(10), "barrier stranded on a dead client"
    fast.close()
    server.shutdown()
