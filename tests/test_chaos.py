"""Chaos: concurrent policy churn + endpoint churn + classification.

The test/runtime/chaos.go analog: the reference restarts agents and
mutates policy under live traffic and asserts the system converges.
Here four thread families hammer one daemon — policy add/delete,
endpoint create/delete, device-batch classification, host fast-path
classification — and afterwards the daemon must still give exactly
the right verdicts.
"""

import threading
import time

import numpy as np

from cilium_tpu.daemon import Daemon
from cilium_tpu.daemon.daemon import DaemonConfig
from cilium_tpu.datapath.engine import make_full_batch
from cilium_tpu.labels import LabelArray
from cilium_tpu.policy.api import (EndpointSelector, IngressRule,
                                   PortProtocol, PortRule, Rule)

DURATION_S = 4.0


def test_concurrent_churn_converges():
    d = Daemon(config=DaemonConfig())
    errors = []
    stop = threading.Event()
    try:
        web = d.endpoint_create(1, ipv4="10.200.5.1",
                                labels=["k8s:app=web"])
        db = d.endpoint_create(2, ipv4="10.200.5.2",
                               labels=["k8s:app=db"])
        base_rule = Rule(
            endpoint_selector=EndpointSelector.parse("app=db"),
            ingress=[IngressRule(
                from_endpoints=[EndpointSelector.parse("app=web")],
                to_ports=[PortRule(ports=[
                    PortProtocol(port="5432", protocol="TCP")])])],
            labels=LabelArray.parse("rule=base"))
        d.policy_add([base_rule])
        assert d.wait_for_quiesce(30)

        def guard(fn):
            def run():
                k = 0
                while not stop.is_set():
                    try:
                        fn(k)
                    except Exception as e:  # noqa: BLE001
                        errors.append(repr(e))
                        return
                    k += 1
            return run

        def policy_churn(k):
            # a second rule flaps; the base rule must keep holding
            r = Rule(endpoint_selector=EndpointSelector.parse("app=web"),
                     ingress=[IngressRule(
                         from_endpoints=[
                             EndpointSelector.parse("app=db")])],
                     labels=LabelArray.parse("rule=flap"))
            d.policy_add([r])
            time.sleep(0.01)
            d.policy_delete(LabelArray.parse("rule=flap"))

        def endpoint_churn(k):
            eid = 50 + (k % 5)
            d.endpoint_create(eid, ipv4=f"10.200.5.{100 + k % 5}",
                              labels=["k8s:app=churn"])
            time.sleep(0.005)
            d.endpoint_delete(eid)

        def device_classify(k):
            batch = make_full_batch(
                endpoint=[db.table_slot], saddr=["10.200.5.1"],
                daddr=["10.200.5.2"], sport=[40000 + (k % 20000)],
                dport=[5432], direction=[0])
            v, *_ = d.datapath.process(batch)
            if int(np.asarray(v)[0]) < 0:
                errors.append(f"allowed flow dropped at k={k}")

        def host_classify(k):
            if d.host_path is None:
                stop.wait(0.01)
                return
            d.host_path.classify(
                db.id, np.array([web.security_identity], np.uint32),
                np.array([5432], np.int32), np.array([6], np.int32),
                np.zeros(1, np.int32))

        threads = [threading.Thread(target=guard(fn), daemon=True)
                   for fn in (policy_churn, endpoint_churn,
                              device_classify, host_classify)]
        for t in threads:
            t.start()
        time.sleep(DURATION_S)
        stop.set()
        for t in threads:
            t.join(timeout=30)
            assert not t.is_alive(), "worker wedged"
        assert not errors, errors[:5]

        # convergence: quiesce, then exact verdicts both tiers
        assert d.wait_for_quiesce(30)
        batch = make_full_batch(
            endpoint=[db.table_slot, db.table_slot],
            saddr=["10.200.5.1", "10.200.5.1"],
            daddr=["10.200.5.2", "10.200.5.2"],
            sport=[61001, 61002], dport=[5432, 80], direction=[0, 0])
        v, *_ = d.datapath.process(batch)
        assert int(np.asarray(v)[0]) >= 0
        assert int(np.asarray(v)[1]) < 0
        if d.host_path is not None:
            hv = d.host_path.classify(
                db.id,
                np.array([web.security_identity] * 2, np.uint32),
                np.array([5432, 80], np.int32),
                np.full(2, 6, np.int32), np.zeros(2, np.int32))
            assert hv[0] >= 0 and hv[1] < 0
    finally:
        stop.set()
        d.shutdown()
