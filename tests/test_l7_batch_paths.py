"""The batched L7 fast paths must agree with the scalar semantics.

Round-4 perf work split encode from match (HTTP/DNS) and vectorized the
Kafka ACL check; these tests pin each fast path to the scalar oracle
(pkg/kafka/policy.go:144-224 semantics for Kafka; the per-request
check_one path for HTTP).
"""

import numpy as np
import pytest

from cilium_tpu.l7.dns import DNSPolicyEngine
from cilium_tpu.l7.http import HTTPPolicyEngine, HTTPRequest
from cilium_tpu.l7.kafka import KafkaPolicyEngine, KafkaRequest
from cilium_tpu.ops.dfa_ops import bucket_cols, encode_strings
from cilium_tpu.policy.api import FQDNSelector, PortRuleHTTP, PortRuleKafka


def _random_kafka_rules(rng):
    rules = []
    for _ in range(rng.integers(1, 6)):
        kind = rng.integers(0, 4)
        kw = {}
        if kind == 0:
            kw["api_key"] = str(rng.choice(["produce", "fetch", "metadata"]))
        elif kind == 1:
            kw["role"] = str(rng.choice(["produce", "consume"]))
        if rng.random() < 0.4:
            kw["api_version"] = str(rng.integers(0, 4))
        if rng.random() < 0.4:
            kw["client_id"] = f"client-{rng.integers(0, 3)}"
        if rng.random() < 0.6:
            kw["topic"] = f"topic-{rng.integers(0, 4)}"
        rules.append(PortRuleKafka(**kw))
    return rules


def test_kafka_vectorized_check_matches_scalar_allows():
    rng = np.random.default_rng(11)
    for trial in range(30):
        eng = KafkaPolicyEngine(_random_kafka_rules(rng))
        reqs = []
        for i in range(64):
            n_topics = int(rng.integers(0, 4))  # includes multi-topic
            reqs.append(KafkaRequest(
                api_key=int(rng.integers(0, 20)),
                api_version=int(rng.integers(0, 4)),
                correlation_id=i,
                topics=[f"topic-{rng.integers(0, 5)}"
                        for _ in range(n_topics)],
                client_id=f"client-{rng.integers(0, 4)}"))
        got = eng.check(reqs)
        want = [eng.allows(r) for r in reqs]
        assert got == want, f"trial {trial} diverged"


def test_kafka_check_empty_rules_allows_all():
    eng = KafkaPolicyEngine([])
    reqs = [KafkaRequest(api_key=0, api_version=0, correlation_id=0,
                         topics=["t"], client_id="c")]
    assert eng.check(reqs) == [True]


def test_kafka_api_key_out_of_mask_range():
    # keys >= 64 must not alias onto low mask bits
    eng = KafkaPolicyEngine([PortRuleKafka(api_key="produce")])  # key 0
    req = KafkaRequest(api_key=64, api_version=0, correlation_id=0,
                       topics=[], client_id="")
    assert eng.check([req]) == [eng.allows(req)] == [False]
    # but a wildcard-key rule still matches any key
    eng2 = KafkaPolicyEngine([PortRuleKafka(client_id="c")])
    req2 = KafkaRequest(api_key=64, api_version=0, correlation_id=0,
                        topics=[], client_id="c")
    assert eng2.check([req2]) == [eng2.allows(req2)] == [True]


def test_bucket_cols_trims_to_power_of_two():
    data = encode_strings(["abcd", "abcdefgh" * 3], 512)
    out = bucket_cols(data)
    assert out.shape == (2, 32)  # 24 bytes -> next pow2 >= 16
    assert (out[0, :4] >= 0).all() and (out[0, 4:] == -1).all()


def test_bucket_cols_keeps_overlong_poison():
    data = encode_strings(["abc", "x" * 100], 8)  # row 1 poisoned
    out = bucket_cols(data, min_cols=4)
    assert (out[1] == -2).any()
    assert out.shape[1] <= 8


def test_bucket_cols_respects_min_and_cap():
    data = encode_strings(["a"], 512)
    assert bucket_cols(data).shape[1] == 16
    data = encode_strings(["a" * 500], 512)
    assert bucket_cols(data).shape[1] == 512  # never widens past cap


def test_http_encoded_path_matches_check_one():
    rules = [PortRuleHTTP(method="GET", path="/api/.*"),
             PortRuleHTTP(method="POST", path="/upload",
                          headers=("x-token secret",)),
             PortRuleHTTP(method="PUT", path="/admin/.*",
                          host="admin\\.example\\.com")]
    eng = HTTPPolicyEngine(rules)
    reqs = [HTTPRequest("GET", "/api/v1/x"),
            HTTPRequest("POST", "/upload"),
            HTTPRequest("POST", "/upload", headers={"X-Token": "secret"}),
            HTTPRequest("POST", "/upload", headers={"X-Token": "wrong"}),
            HTTPRequest("PUT", "/admin/panel", host="admin.example.com"),
            HTTPRequest("PUT", "/admin/panel", host="evil.example.com"),
            HTTPRequest("DELETE", "/api/v1/x")]
    data, hdata = eng.encode(reqs)
    got = eng.check_encoded(data, hdata, len(reqs)).tolist()
    want = [eng.check_one(r) for r in reqs]
    assert got == want == [True, False, True, False, True, False, False]


def test_kafka_empty_string_topic_is_still_a_topic():
    # topics=[""] must behave like any unknown topic (scalar keeps it
    # in `remaining`), not like a topicless request
    eng = KafkaPolicyEngine([PortRuleKafka(topic="logs")])
    req = KafkaRequest(api_key=0, api_version=0, correlation_id=0,
                       topics=[""], client_id="")
    assert eng.check([req]) == [eng.allows(req)] == [False]
    # a topicless rule still covers it
    eng2 = KafkaPolicyEngine([PortRuleKafka(client_id="")])
    assert eng2.check([req]) == [eng2.allows(req)] == [True]


def test_http_allow_all_engine_encoded_path():
    eng = HTTPPolicyEngine([])
    reqs = [HTTPRequest("GET", "/x"), HTTPRequest("POST", "/y")]
    data, hdata = eng.encode(reqs)
    assert data is None and hdata is None
    assert eng.check_encoded(data, hdata, 2).tolist() == [True, True]
    with pytest.raises(ValueError):
        eng.match_device(data, hdata)


def test_dns_selectorless_engine_encoded_path():
    eng = DNSPolicyEngine([])
    assert eng.encode(["a.com"]) is None
    assert eng.match_encoded(None, 3).shape == (3, 0)
    with pytest.raises(ValueError):
        eng.match_device(None)


def test_scalar_dfa_matches_device_dfa():
    """The C++ walker (live-request path) must agree with the device
    kernel on the same compiled tables, byte for byte."""
    import jax.numpy as jnp
    from cilium_tpu.compiler.regexc import compile_regex_set
    from cilium_tpu.native import ScalarDFA
    from cilium_tpu.ops.dfa_ops import dfa_match, encode_strings
    pats = ["GET\x00/a.*", "(ab|cd)+x?", ".*zz.*", "[a-m]{3,9}"]
    c = compile_regex_set(pats)
    scalar = ScalarDFA(c)
    rng = np.random.default_rng(4)
    texts = ["GET\x00/abc", "ababx", "qqzzq", "abcdef", "", "zz",
             "GET\x00/b", "cdx"]
    texts += ["".join(chr(rng.integers(97, 123)) for _ in range(
        rng.integers(0, 12))) for _ in range(40)]
    data = jnp.asarray(encode_strings(texts, 32))
    dev = np.asarray(dfa_match(jnp.asarray(c.table),
                               jnp.asarray(c.accept),
                               jnp.asarray(c.starts), data))
    for i, t in enumerate(texts):
        got = scalar.match(t.encode())
        assert (got == dev[i]).all(), (t, got, dev[i])


def test_http_check_one_scalar_matches_batched():
    rules = [PortRuleHTTP(method="GET", path="/api/.*"),
             PortRuleHTTP(method="POST", path="/up",
                          headers=("x-token secret",)),
             PortRuleHTTP(method="PUT", path="/admin/.*",
                          host="a\\.example\\.com")]
    eng = HTTPPolicyEngine(rules)
    assert eng._scalar is not None, "native walker must build here"
    reqs = [HTTPRequest("GET", "/api/1"),
            HTTPRequest("GET", "/api/" + "x" * 600),  # overlong line
            HTTPRequest("POST", "/up", headers={"X-Token": "secret"}),
            HTTPRequest("POST", "/up", headers={"X-Token": "no"}),
            HTTPRequest("POST", "/up"),
            HTTPRequest("PUT", "/admin/x", host="a.example.com"),
            HTTPRequest("PUT", "/admin/x", host="b.example.com"),
            HTTPRequest("HEAD", "/api/1")]
    batched = eng.check(reqs)
    for i, r in enumerate(reqs):
        assert eng.check_one(r) == bool(batched[i]), (i, r)


def test_check_one_overlong_headers_keep_headerless_rules():
    """Review regression: an overlong header block poisons only the
    header patterns — a matching header-less rule must still allow,
    exactly like the batched path."""
    rules = [PortRuleHTTP(method="GET", path="/api/.*"),
             PortRuleHTTP(method="POST", path="/up",
                          headers=("x-token secret",))]
    eng = HTTPPolicyEngine(rules)
    big = {"cookie": "x" * 2000}
    allowed_req = HTTPRequest("GET", "/api/1", headers=big)
    denied_req = HTTPRequest("POST", "/up", headers=big)
    assert bool(eng.check([allowed_req])[0]) is True
    assert eng.check_one(allowed_req) is True
    assert bool(eng.check([denied_req])[0]) is False
    assert eng.check_one(denied_req) is False


def test_dns_allowed_one_matches_batched():
    eng = DNSPolicyEngine([FQDNSelector(match_pattern="*.example.com"),
                           FQDNSelector(match_name="db.internal")])
    assert eng._scalar is not None
    names = ["a.example.com", "db.internal", "DB.INTERNAL.",
             "evil.com", "x" * 300 + ".example.com"]
    batched = eng.allowed(names)
    for i, n in enumerate(names):
        assert eng.allowed_one(n) == bool(batched[i]), n
    assert DNSPolicyEngine([]).allowed_one("a.com") is False


def test_dns_encoded_path_matches_allowed():
    eng = DNSPolicyEngine([FQDNSelector(match_pattern="*.example.com"),
                           FQDNSelector(match_name="db.internal")])
    names = ["a.example.com", "db.internal", "evil.com",
             "deep.sub.example.com"]
    enc = eng.encode(names)
    got = eng.match_encoded(enc, len(names)).any(axis=1).tolist()
    assert got == eng.allowed(names).tolist()
