"""The incident flight recorder (observability/events.py) + serving
SLO tier (observability/slo.py), and the loudness lint: every degraded
condition status() can report must have a matching flight-recorder
event type AND a metric series — a new failure mode can't ship silent.
"""

import threading
import time

import pytest

from cilium_tpu.observability.events import (DEGRADED_SIGNALS,
                                             EVENT_TYPES,
                                             FlightRecorder, recorder)
from cilium_tpu.observability.slo import SLOTracker
from cilium_tpu.utils import metrics as metrics_mod


# ----------------------------------------------------- recorder core

class TestFlightRecorder:
    def test_seq_monotonic_and_forward_paging(self):
        fr = FlightRecorder(capacity=16)
        evs = [fr.record("dataplane-breaker-trip", detail=f"e{i}",
                         shard=i % 2) for i in range(5)]
        assert [e.seq for e in evs] == [1, 2, 3, 4, 5]
        got = fr.events(since=2, limit=0)
        assert [e.seq for e in got] == [3, 4, 5]
        # type + shard filters compose with the cursor
        got = fr.events(since=0, event_type="dataplane-breaker-trip",
                        shard=1)
        assert [e.seq for e in got] == [2, 4]
        assert fr.last_seq == 5

    def test_bounded_ring_evicts_oldest_and_accounts(self):
        fr = FlightRecorder(capacity=4)
        for i in range(10):
            fr.record("serving-overload", state="on", i=i)
        assert fr.stats()["ringed"] == 4
        assert fr.evicted == 6
        # the surviving events are the NEWEST, cursors intact
        assert [e.seq for e in fr.events(limit=0)] == [7, 8, 9, 10]

    def test_eviction_accounting_split_by_evicted_type(self):
        """The dropped accounting names WHICH type overran the ring: a
        noisy emitter flooding the recorder shows up as its own type's
        eviction count, not an anonymous aggregate a quieter type
        could hide behind."""
        fr = FlightRecorder(capacity=4)
        for _ in range(6):
            fr.record("serving-overload", state="on")
        for _ in range(2):
            fr.record("map-pressure-warning", map="ct", shard=None)
        # 8 recorded, 4 survive; the 4 evicted are the oldest — all
        # the noisy emitter's
        st = fr.stats()
        assert fr.evicted == 4
        assert st["evicted-by-type"] == {"serving-overload": 4}
        # push the quieter type out too: both types now accounted
        for _ in range(4):
            fr.record("serving-overload", state="on")
        by_type = fr.stats()["evicted-by-type"]
        assert by_type["map-pressure-warning"] == 2
        assert sum(by_type.values()) == fr.evicted

    def test_eviction_counter_labeled_by_type(self):
        ctr = metrics_mod.registry._metrics[
            "cilium_tpu_flight_recorder_dropped_total"]
        before = ctr.value(labels={"type": "serving-overload"})
        fr = FlightRecorder(capacity=2)
        for _ in range(5):
            fr.record("serving-overload", state="on")
        assert ctr.value(
            labels={"type": "serving-overload"}) == before + 3

    def test_undeclared_type_raises(self):
        fr = FlightRecorder()
        with pytest.raises(ValueError):
            fr.record("made-up-event")

    def test_event_rendering_and_wire_dict(self):
        fr = FlightRecorder()
        e = fr.record("kvstore-degraded", detail="etcd gone",
                      shard=None, outage=3)
        d = e.to_dict()
        assert d["type"] == "kvstore-degraded"
        assert d["attrs"] == {"outage": 3}
        assert "kvstore-degraded: etcd gone (outage=3)" \
            in e.describe()
        e2 = fr.record("dataplane-degraded", shard=2)
        assert e2.describe().startswith("[shard 2] ")
        assert len(fr.timeline()) == 2

    def test_trace_id_rides_along(self):
        from cilium_tpu.observability.tracer import tracer
        tracer.configure(enabled=True)
        fr = FlightRecorder()
        with tracer.span("incident-test"):
            e = fr.record("drift-audit", status="FAILING",
                          divergences=1)
        assert e.trace_id != ""

    def test_global_recorder_counts_metric(self):
        before = metrics_mod.registry._metrics[
            "cilium_tpu_flight_recorder_events_total"].value(
            labels={"type": "map-pressure-warning"})
        recorder.record("map-pressure-warning", map="ct", shard=None)
        after = metrics_mod.registry._metrics[
            "cilium_tpu_flight_recorder_events_total"].value(
            labels={"type": "map-pressure-warning"})
        assert after == before + 1

    def test_thread_safe_unique_seqs(self):
        fr = FlightRecorder(capacity=4096)
        out = []

        def spin():
            out.extend(fr.record("serving-overload", state="on").seq
                       for _ in range(200))

        threads = [threading.Thread(target=spin) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(set(out)) == 800


# ------------------------------------------------------- SLO tracker

class TestSLOTracker:
    def test_latency_percentiles_and_breaches(self):
        slo = SLOTracker()
        slo.configure(objective_s=0.010, error_budget=0.1)
        for _ in range(90):
            slo.observe("lane-a", 0.001)
        for _ in range(10):
            slo.observe("lane-a", 0.050)   # breach
        snap = slo.snapshot()["lanes"]["lane-a"]
        assert snap["requests"] == 100
        assert snap["breaches"] == 10
        # 10% breaches / 10% budget = burn rate 1.0
        assert snap["burn-rate"] == pytest.approx(1.0, abs=0.01)
        assert snap["p50-us"] == pytest.approx(1000.0, rel=0.2)
        assert snap["p99-us"] >= 10_000.0
        assert snap["worst-us"] == pytest.approx(50_000.0, rel=0.01)

    def test_lane_objective_from_deadline(self):
        slo = SLOTracker()
        slo.configure(objective_s=1.0, error_budget=0.001)
        # an explicit per-lane objective (the admission deadline)
        # overrides the default
        slo.observe("lane-d", 0.02, objective_s=0.01)
        snap = slo.snapshot()["lanes"]["lane-d"]
        assert snap["objective-ms"] == 10.0
        assert snap["breaches"] == 1

    def test_queue_ring_bounded_and_sampled(self):
        slo = SLOTracker()
        for i in range(300):
            slo.sample_queue("lane-q", queued=i, inflight=i % 3,
                             pending_weight=i * 2, shard=1)
        ring = slo.queue_ring("lane-q")
        assert len(ring) == 256           # bounded
        assert ring[-1]["pending"] == 299 * 2
        snap = slo.snapshot()["lanes"]["lane-q"]
        assert snap["shard"] == 1
        assert snap["queue"]["inflight"] == 299 % 3

    def test_top_lines_render(self):
        slo = SLOTracker()
        slo.observe("verdict-s0", 0.002, shard=0)
        slo.sample_queue("verdict-s0", 4, 2, 128, shard=0)
        lines = slo.top_lines()
        assert "LANE" in lines[0] and "BURN" in lines[0]
        assert any("verdict-s0" in line for line in lines[1:])

    def test_dispatcher_feeds_the_tier(self):
        """Plumbing: a ContinuousDispatcher resolution observes the
        ticket latency into the process tracker and samples the queue
        — no engine needed (host-only lane)."""
        from cilium_tpu.datapath.serving import ContinuousDispatcher
        from cilium_tpu.observability.slo import slo_tracker
        lane = f"slo-test-{time.monotonic_ns()}"
        d = ContinuousDispatcher(
            launch=lambda items, total: list(items),
            finalize=lambda handle, weights: [i * 2 for i in handle],
            deny=lambda item: -1, lane=lane)
        try:
            tickets = [d.submit(i) for i in range(8)]
            for i, t in enumerate(tickets):
                assert t.result(timeout=10.0) == i * 2
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                snap = slo_tracker.snapshot()["lanes"].get(lane)
                if snap and snap["requests"] >= 8 and \
                        snap["queue-samples"] > 0:
                    break
                time.sleep(0.01)
            assert snap["requests"] >= 8
            assert snap["queue-samples"] > 0
            assert snap["p99-us"] > 0.0
        finally:
            d.close()


# ------------------------------------------------------ loudness lint

SIGNAL_KEYS = {"state", "status", "mode", "warnings", "drift-audit"}


def _degraded_sections(status):
    """status() sections that can report a degraded condition: any
    dict section carrying a state/status/mode/warnings signal key."""
    return {k for k, v in status.items()
            if isinstance(v, dict) and SIGNAL_KEYS & set(v)}


def test_loudness_lint_every_degraded_signal_has_event_and_metric():
    """A live daemon's status() is introspected for every section
    that reports a degraded condition; each must be covered by
    DEGRADED_SIGNALS with declared flight-recorder event types and
    registered metric series — shipping a new failure mode without a
    timeline event and a metric is a test failure, not a review nit."""
    from cilium_tpu.daemon import Daemon
    from cilium_tpu.utils.option import DaemonConfig
    d = Daemon(config=DaemonConfig(
        state_dir="", drift_audit_interval_s=0,
        ct_checkpoint_interval_s=0))
    try:
        sections = _degraded_sections(d.status())
    finally:
        d.shutdown()
    assert sections, "status() lost its degraded-signal sections"
    uncovered = sections - set(DEGRADED_SIGNALS)
    assert not uncovered, (
        "status() sections reporting degraded conditions without "
        "flight-recorder coverage (add them to "
        f"observability/events.py DEGRADED_SIGNALS): {uncovered}")
    stale = set(DEGRADED_SIGNALS) - sections
    assert not stale, (
        f"DEGRADED_SIGNALS names status() sections that no longer "
        f"exist: {stale}")
    with metrics_mod.registry._lock:
        registered = set(metrics_mod.registry._metrics)
    for section, cover in DEGRADED_SIGNALS.items():
        assert cover["events"], section
        for ev in cover["events"]:
            assert ev in EVENT_TYPES, (
                f"{section} names undeclared event type {ev!r}")
        assert cover["metrics"], section
        for m in cover["metrics"]:
            assert m in registered, (
                f"{section} names unregistered metric {m!r}")


def test_every_event_type_belongs_to_a_degraded_signal():
    """The other direction: no orphan event types — each declared
    type is reachable from some degraded condition's coverage, so
    EVENT_TYPES can't accrete stale docs."""
    covered = {ev for cover in DEGRADED_SIGNALS.values()
               for ev in cover["events"]}
    orphans = set(EVENT_TYPES) - covered
    assert not orphans, (
        f"EVENT_TYPES declares types no DEGRADED_SIGNALS entry "
        f"covers: {orphans}")


def test_event_types_have_descriptions():
    for name, help_text in EVENT_TYPES.items():
        assert help_text and len(help_text) > 10, name
