"""Device-resident traffic analytics (cilium_tpu/analytics/): the
sketch-based heavy-hitter / scan / cardinality plane fused into the
verdict pipelines.

- **Fused parity** — the device AnalyticsState buffer replays
  bit-exactly against the numpy oracle over multiple batches and
  epoch swaps, v4 AND v6, with flows + threat + provenance fused
  (the full-pipeline shape).
- **Disabled path** — enable->disable lowers the byte-identical
  pre-analytics program (lowered-HLO-asserted).
- **Epoch protocol** — a swap is one control-cell write: the
  quiesced section is immutable under continued serving load, new
  batches land only in the write section.
- **Decode views** — talkers / scanners / spreaders / prefixes name
  planted offenders; count-min estimates never under-count.
- **Mesh merge** — sketch counts add, key tables and registers max,
  order-free; a degraded shard degrades the answer to a flagged
  ``partial`` (fail-open), its breaker opens, serving never pauses.
- **Live-daemon journey** — drain controller -> capped top-K gauge
  export -> edge-triggered heavy-hitter / scan-suspect flight-
  recorder events -> REST + CLI top views.
"""

import numpy as np
import pytest

from cilium_tpu.analytics import decode as adec
from cilium_tpu.analytics.oracle import (oracle_analytics_step,
                                         oracle_swap_epoch)
from cilium_tpu.analytics.stage import (KS_IDENTITY, MET_BYTES,
                                        N_KEYSPACES, N_METRICS,
                                        epoch_rows)
from cilium_tpu.datapath.engine import Datapath, make_full_batch6
from cilium_tpu.datapath.pipeline import PACKED_FIELDS
from cilium_tpu.datapath.verdict import VERDICT_DROP
from cilium_tpu.policy.mapstate import (EGRESS, INGRESS, PolicyKey,
                                        PolicyMapState,
                                        PolicyMapStateEntry)
from cilium_tpu.threat import ThreatConfig, default_model

HTTP_ID, DNS_ID = 777, 888
WORLD = 2
EP_IDENTITY = 1234
WIDTH = 1 << 10
DEPTH, LANES, STRIPE = 2, 4, 4


def _policy():
    st = PolicyMapState()
    st[PolicyKey(identity=HTTP_ID, dest_port=80, nexthdr=6,
                 direction=INGRESS)] = PolicyMapStateEntry()
    st[PolicyKey(identity=DNS_ID, dest_port=53, nexthdr=17,
                 direction=EGRESS)] = PolicyMapStateEntry()
    return st


def _engine(analytics=True, flows=True, provenance=True, threat=True,
            stripe=STRIPE, ct_slots=1 << 10):
    dp = Datapath(ct_slots=ct_slots)
    dp.telemetry_enabled = False
    if provenance:
        dp.enable_provenance()
    if flows:
        dp.enable_flow_aggregation(slots=1 << 8, claim_every=1)
    if threat:
        # shadow mode: the threat stage is fused (scores every row)
        # but never flips a verdict, so the host verdict twin below
        # stays the plain policy+CT oracle
        dp.enable_threat(default_model(ThreatConfig()), buckets=64,
                         window_s=8)
    if analytics:
        dp.enable_analytics(width=WIDTH, depth=DEPTH, lanes=LANES,
                            stripe=stripe)
    dp.load_policy([_policy()], revision=1, ipcache_prefixes={
        "10.0.0.0/8": HTTP_ID, "20.0.0.0/8": DNS_ID})
    dp.set_endpoint_identity(0, EP_IDENTITY)
    return dp


def _traffic(rng, n, sport0):
    """Mixed batch: allowed HTTP ingress (10/8 -> 777), allowed DNS
    egress (daddr 20/8 -> 888), and WORLD-sourced denied rows."""
    kind = rng.integers(0, 3, n)           # 0 http, 1 dns, 2 denied
    is_http = kind == 0
    is_dns = kind == 1
    saddr = np.where(is_http, (10 << 24) | 5, (50 << 24) | 9) \
        .astype(np.uint32)
    daddr = np.where(is_dns, (20 << 24) | 9, (10 << 24) | 8) \
        .astype(np.uint32)
    recs = {
        "endpoint": np.zeros(n, np.int32),
        "saddr": saddr.view(np.int32),
        "daddr": daddr.view(np.int32),
        "sport": (sport0 + np.arange(n)).astype(np.int32),
        "dport": np.where(is_http, 80,
                          np.where(is_dns, 53,
                                   rng.integers(1, 65536, n))
                          ).astype(np.int32),
        "proto": np.where(is_dns, 17, 6).astype(np.int32),
        "direction": np.where(is_http, 0, 1).astype(np.int32),
        "tcp_flags": np.where(rng.random(n) < 0.5, 0x02, 0x10)
        .astype(np.int32),
        "length": rng.integers(60, 1500, n).astype(np.int32),
        "is_fragment": np.zeros(n, np.int32),
    }
    return _stage_of(recs), recs


def _stage_of(recs):
    n = recs["endpoint"].shape[0]
    stage = np.empty((len(PACKED_FIELDS), n), np.int32)
    for i, f in enumerate(PACKED_FIELDS):
        stage[i] = recs[f]
    return stage


def _identities(recs):
    """Host ipcache twin: resolved peer identity per row."""
    sa = recs["saddr"].view(np.uint32)
    da = recs["daddr"].view(np.uint32)
    peer = np.where(recs["direction"] == 0, sa, da)
    ident = np.full(peer.shape[0], WORLD, np.int32)
    ident[(peer >> 24) == 10] = HTTP_ID
    ident[(peer >> 24) == 20] = DNS_ID
    return ident


def _policy_verdict(ident, recs):
    """Host policy twin of the two installed rules."""
    ok = ((ident == HTTP_ID) & (recs["dport"] == 80) &
          (recs["proto"] == 6) & (recs["direction"] == 0)) | \
         ((ident == DNS_ID) & (recs["dport"] == 53) &
          (recs["proto"] == 17) & (recs["direction"] == 1))
    return np.where(ok, 0, VERDICT_DROP).astype(np.int32)


def _established_from_ct(dp, recs):
    """Pre-batch established view from the live CT dump (forward
    tuples only; test traffic never sends replies)."""
    live = {(e["saddr"], e["daddr"], e["sport"], e["dport"],
             e["proto"]) for e in dp.map_dump("ct", max_entries=1 << 14)}
    sa = recs["saddr"].view(np.uint32)
    da = recs["daddr"].view(np.uint32)
    return np.array([
        (int(sa[i]), int(da[i]), int(recs["sport"][i]),
         int(recs["dport"][i]), int(recs["proto"][i])) in live
        for i in range(sa.shape[0])], bool)


def _blank(width=WIDTH, depth=DEPTH, lanes=LANES):
    """Fresh host mirror of the [R, W] AnalyticsState buffer."""
    return np.zeros((2 * epoch_rows(depth, lanes) + 1, width),
                    np.int32)


# ------------------------------------------------------ fused parity

@pytest.mark.parametrize("seed", [21, 22, 23])
def test_fused_parity_vs_oracle_v4(seed):
    """The device analytics buffer (sketches, key tables, cardinality
    registers AND the epoch control cell) replays bit-exactly against
    the numpy oracle over multiple batches, shifting stripe phases,
    and a mid-test epoch swap — flows + threat + provenance fused."""
    rng = np.random.default_rng(seed)
    dp = _engine()
    mirror = _blank()
    now = 1000 + seed          # seeds land on different stripe phases
    sport0 = 20000
    for batch in range(4):
        if batch == 2:
            # mid-test epoch swap: device and oracle flip in lockstep
            assert dp.swap_analytics_epoch() == \
                oracle_swap_epoch(mirror, DEPTH, LANES)
        n = 96
        stage, recs = _traffic(rng, n, sport0)
        sport0 += n
        ident = _identities(recs)
        verdict = np.where(_established_from_ct(dp, recs), 0,
                           _policy_verdict(ident, recs))
        v, e, got_ident, _nat = dp.process_packed(stage, now=now)
        # the oracle's inputs are the HOST twins — assert the device
        # agrees before folding them, so parity is end-to-end
        np.testing.assert_array_equal(np.asarray(got_ident), ident)
        np.testing.assert_array_equal(np.asarray(v), verdict)
        oracle_analytics_step(
            mirror, identity=ident, dport=recs["dport"],
            proto=recs["proto"], sport=recs["sport"],
            length=recs["length"], verdict=verdict,
            saddr_key=recs["saddr"], daddr_key=recs["daddr"],
            now=now, depth=DEPTH, lanes=LANES, stripe=STRIPE)
        np.testing.assert_array_equal(
            np.asarray(dp.analytics_state.state), mirror,
            err_msg=f"analytics state diverged (batch {batch})")
        now += 3


def test_fused_parity_vs_oracle_v6():
    """The v6 twin folds through the shared stage; the address words
    enter the flow hash and dst-prefix key as their CT folds."""
    from cilium_tpu.datapath.pipeline import fold6
    dp = Datapath(ct_slots=1 << 8)
    dp.telemetry_enabled = False
    dp.enable_provenance()
    dp.enable_threat(default_model(ThreatConfig()), buckets=64,
                     window_s=8)
    dp.enable_analytics(width=WIDTH, depth=DEPTH, lanes=LANES,
                        stripe=STRIPE)
    dp.load_policy([_policy()], revision=1)
    dp.load_ipcache6({"fd00::/16": HTTP_ID})
    dp.set_endpoint_identity(0, EP_IDENTITY)
    n = 32
    dports = [80 if i % 2 == 0 else 81 for i in range(n)]
    pkt = make_full_batch6(
        endpoint=[0] * n, saddr=["fd00::5"] * n,
        daddr=["fd00::9"] * n, sport=[30000 + i for i in range(n)],
        dport=dports, proto=[6] * n, direction=[0] * n)
    mirror = _blank()
    ident = np.full(n, HTTP_ID, np.int32)
    verdict = np.where(np.array(dports) == 80, 0,
                       VERDICT_DROP).astype(np.int32)
    v, e, got_ident, _nat = dp.process6(pkt, now=501)
    np.testing.assert_array_equal(np.asarray(got_ident), ident)
    np.testing.assert_array_equal(np.asarray(v), verdict)
    oracle_analytics_step(
        mirror, identity=ident, dport=np.asarray(pkt.dport),
        proto=np.asarray(pkt.proto), sport=np.asarray(pkt.sport),
        length=np.asarray(pkt.length), verdict=verdict,
        saddr_key=np.asarray(fold6(pkt.saddr)),
        daddr_key=np.asarray(fold6(pkt.daddr)),
        now=501, depth=DEPTH, lanes=LANES, stripe=STRIPE)
    np.testing.assert_array_equal(np.asarray(dp.analytics_state.state),
                                  mirror)


# ---------------------------------------------------- disabled path

def test_disabled_path_is_byte_identical():
    import jax.numpy as jnp
    base = _engine(analytics=False, flows=False, threat=False)
    tog = _engine(flows=False, threat=False)
    stage = jnp.asarray(np.zeros((10, 16), np.int32))
    en_txt = tog._step_packed.lower(
        *tog._lower_args_packed(stage)).as_text()
    tog.disable_analytics()
    base_txt = base._step_packed.lower(
        *base._lower_args_packed(stage)).as_text()
    tog_txt = tog._step_packed.lower(
        *tog._lower_args_packed(stage)).as_text()
    assert tog_txt == base_txt
    assert en_txt != base_txt
    assert base.dispatch_leaf_counts() == tog.dispatch_leaf_counts()


# ---------------------------------------------------- epoch protocol

def test_epoch_swap_quiesced_section_immutable_under_load():
    """A swap is one control-cell write: host decodes read the
    quiesced section while serving keeps folding batches into the
    OTHER section — and the next swap zeroes only the section about
    to be written."""
    dp = _engine(flows=False, threat=False, stripe=1)
    rng = np.random.default_rng(5)
    stage, _ = _traffic(rng, 64, 40000)
    dp.process_packed(stage, now=100)
    q = dp.swap_analytics_epoch()
    snap = dp.analytics_snapshot()
    sec_q = adec.epoch_section(snap, q, DEPTH, LANES).copy()
    assert sec_q.any(), "the drained epoch must hold the traffic"
    assert adec.write_epoch(snap, DEPTH, LANES) == 1 - q
    assert dp.analytics_report()["write-epoch"] == 1 - q
    # serving continues: new batches land only in the write section
    stage2, _ = _traffic(rng, 64, 50000)
    dp.process_packed(stage2, now=104)
    dp.process_packed(stage2, now=105)
    snap2 = dp.analytics_snapshot()
    np.testing.assert_array_equal(
        adec.epoch_section(snap2, q, DEPTH, LANES), sec_q,
        err_msg="the quiesced section moved under serving load")
    sec_w = adec.epoch_section(snap2, 1 - q, DEPTH, LANES).copy()
    assert sec_w.any()
    # the next swap zeroes the STALE section, quiesces the live one
    q2 = dp.swap_analytics_epoch()
    assert q2 == 1 - q
    snap3 = dp.analytics_snapshot()
    assert not adec.epoch_section(snap3, q, DEPTH, LANES).any()
    np.testing.assert_array_equal(
        adec.epoch_section(snap3, q2, DEPTH, LANES), sec_w)


# ------------------------------------------------------ decode views

def _plant(state, identity, dports, sports, saddrs, lengths,
           dropped=False):
    n = len(dports)
    oracle_analytics_step(
        state, identity=np.full(n, identity, np.int64),
        dport=np.array(dports, np.int64),
        proto=np.full(n, 6, np.int64),
        sport=np.array(sports, np.int64),
        length=np.array(lengths, np.int64),
        verdict=np.full(n, VERDICT_DROP if dropped else 0, np.int64),
        saddr_key=np.array(saddrs, np.int64),
        daddr_key=np.full(n, (20 << 24) | 9, np.int64),
        now=0, depth=DEPTH, lanes=LANES, stripe=1)


def test_decode_views_name_the_planted_offenders():
    """Talkers / scanners / spreaders / prefixes over a section with
    three planted behaviors: a byte-heavy talker, a dport-sweeping
    scanner (dropped traffic), and a flow-fanning spreader."""
    state = _blank()
    # 777: heavy talker — 50 big frames, ONE flow
    _plant(state, 777, [443] * 50, [40000] * 50,
           [(10 << 24) | 5] * 50, [1400] * 50)
    # 999: port scanner — 40 distinct dports, tiny dropped frames
    _plant(state, 999, list(range(1, 41)), [51000] * 40,
           [(50 << 24) | 9] * 40, [60] * 40, dropped=True)
    # 555: spreader — 256 distinct 5-tuples on one service port
    _plant(state, 555, [53] * 256, list(range(10000, 10256)),
           list(range(1, 257)), [80] * 256)
    sec = adec.epoch_section(state, 0, DEPTH, LANES)

    talkers = adec.top_talkers(sec, DEPTH, k=3, metric="bytes")
    assert talkers[0]["identity"] == 777
    # count-min is an upper bound: it may over-count, never under
    assert talkers[0]["count"] >= 50 * 1400
    drops = adec.top_talkers(sec, DEPTH, k=3, metric="drops")
    assert drops[0]["identity"] == 999
    assert drops[0]["count"] >= 40

    scan = adec.top_scanners(sec, DEPTH, k=3, min_dports=16)
    assert scan[0]["identity"] == 999
    assert scan[0]["dports"] >= 16 and scan[0]["suspect"]
    assert all(not e["suspect"] for e in scan if e["identity"] == 777)

    spread = adec.top_spreaders(sec, DEPTH, LANES, k=3)
    assert spread[0]["identity"] == 555
    assert spread[0]["flows"] > 0

    prefixes = adec.top_prefixes(sec, DEPTH, k=3, metric="bytes")
    assert prefixes[0]["prefix"] == ((20 << 24) | 9) >> 8
    with pytest.raises(KeyError):
        adec.decode_view(sec, "nonsense", DEPTH, LANES)


def test_mesh_merge_adds_sketches_maxes_registers_order_free():
    a, b = _blank(width=256), _blank(width=256)
    _plant(a, 777, [443] * 10, [40000] * 10, [(10 << 24) | 5] * 10,
           [100] * 10)
    _plant(b, 777, [443] * 5, [45000 + i for i in range(5)],
           [(10 << 24) | 6] * 5, [100] * 5)
    sec_a = adec.epoch_section(a, 0, DEPTH, LANES)
    sec_b = adec.epoch_section(b, 0, DEPTH, LANES)
    merged = adec.merge_sections([sec_a, sec_b], DEPTH, LANES)
    n_sketch = N_KEYSPACES * N_METRICS * DEPTH
    np.testing.assert_array_equal(
        merged[:n_sketch],
        sec_a[:n_sketch].astype(np.int64) + sec_b[:n_sketch])
    np.testing.assert_array_equal(
        merged[n_sketch:], np.maximum(sec_a[n_sketch:],
                                      sec_b[n_sketch:]))
    # shard arrival order is irrelevant
    np.testing.assert_array_equal(
        merged, adec.merge_sections([sec_b, sec_a], DEPTH, LANES))
    # the merged view answers with the mesh-wide count
    assert adec.cm_query(merged, KS_IDENTITY, MET_BYTES,
                         np.array([777]), DEPTH)[0] >= 15 * 100
    t = adec.top_talkers(merged, DEPTH, k=1, metric="bytes")
    assert t[0]["identity"] == 777 and t[0]["count"] >= 15 * 100


# -------------------------------------------- sharded mesh, fail-open

def test_sharded_merge_and_degraded_shard_fails_open():
    """Each shard folds into its OWN buffer; one mesh-wide query
    merges them.  A shard whose buffer becomes unreadable degrades
    the answer to a flagged ``partial`` served from the remaining
    shards — fail-open, breaker opens after repeated failures, and
    the healthy shard keeps serving throughout."""
    from cilium_tpu.parallel.sharded import ShardedDatapath
    p = ShardedDatapath(n_shards=2, ct_slots=1 << 8)
    p.telemetry_enabled = False
    p.enable_analytics(width=1 << 8, depth=DEPTH, lanes=LANES,
                       stripe=1)
    p.load_policy([_policy() for _ in range(4)], revision=1,
                  ipcache_prefixes={"10.0.0.0/8": HTTP_ID,
                                    "20.0.0.0/8": DNS_ID})
    n = 32

    def _recs(endpoint, direction, sport0):
        return {
            "endpoint": np.full(n, endpoint, np.int32),
            "saddr": np.full(n, (10 << 24) | 5, np.uint32)
            .view(np.int32),
            "daddr": np.full(n, (20 << 24) | 9, np.uint32)
            .view(np.int32),
            "sport": (sport0 + np.arange(n)).astype(np.int32),
            "dport": np.full(n, 80 if direction == 0 else 53,
                             np.int32),
            "proto": np.full(n, 6 if direction == 0 else 17, np.int32),
            "direction": np.full(n, direction, np.int32),
            "tcp_flags": np.full(n, 0x02, np.int32),
            "length": np.full(n, 100, np.int32),
            "is_fragment": np.zeros(n, np.int32),
        }

    try:
        # shard 0 sees identity 777 (ingress), shard 1 identity 888
        # (egress) — shard-local buffers, mesh-wide answer
        p.classify_records(_recs(0, 0, 56000), n)
        p.classify_records(_recs(1, 1, 57000), n)
        assert np.asarray(p.shards[0].analytics_state.state).any()
        assert np.asarray(p.shards[1].analytics_state.state).any()
        out = p.analytics_query(view="talkers", k=10, metric="bytes",
                                swap=True)
        assert out["partial"] is False
        assert all(s["status"] == "ok"
                   for s in out["shards"].values())
        ids = {e["identity"] for e in out["entries"]}
        assert {HTTP_ID, DNS_ID} <= ids, \
            "the merged view must cover BOTH shards' traffic"
        # shard 1's device buffer goes unreadable: the next query is
        # a flagged partial served from shard 0 alone (swap-free —
        # the quiesced sections still hold the drained epoch)
        p.shards[1].analytics_state = None
        out2 = p.analytics_query(view="talkers", k=10,
                                 metric="bytes", swap=False)
        assert out2["partial"] is True
        assert out2["shards"]["1"]["status"] == "error"
        assert out2["shards"]["0"]["status"] == "ok"
        ids2 = {e["identity"] for e in out2["entries"]}
        assert HTTP_ID in ids2 and DNS_ID not in ids2
        # a second failure trips the shard's breaker; the mesh answer
        # stays partial without even touching the dead shard
        p.analytics_sections(swap=False)
        out3 = p.analytics_sections(swap=False)
        assert out3["shards"]["1"]["status"] == "breaker-open"
        assert out3["partial"] is True
        assert p.analytics_report()["open-breakers"] == 1
        # the healthy shard never paused: serving still answers
        v, _i = p.classify_records(_recs(0, 0, 58000), n)
        assert v.shape[0] == n
    finally:
        p.serving().close()


# ------------------------------------------------ live-daemon journey

def test_live_daemon_analytics_journey(capsys):
    """traffic -> drain -> gauges/events -> REST -> CLI: the full
    operator loop on a live agent with analytics enabled.  Heavy-
    hitter and scan-suspect transitions are edge-triggered (a
    sustained hitter is ONE event), and the top-K byte gauge is
    cardinality-capped (evicted identities zero out)."""
    from cilium_tpu.cli import Client
    from cilium_tpu.cli import main as cli_main
    from cilium_tpu.daemon import Daemon
    from cilium_tpu.daemon.rest import APIServer
    from cilium_tpu.observability.events import (
        EVENT_TRAFFIC_HEAVY_HITTER, EVENT_TRAFFIC_SCAN_SUSPECT,
        recorder)
    from cilium_tpu.utils.metrics import (ANALYTICS_SCAN_SUSPECTS,
                                          ANALYTICS_TOP_BYTES)
    from cilium_tpu.utils.option import DaemonConfig

    def _count(ev_type):
        return sum(1 for ev in recorder.events(limit=0)
                   if ev.type == ev_type)

    d = Daemon(config=DaemonConfig(
        state_dir="", drift_audit_interval_s=0,
        ct_checkpoint_interval_s=0, enable_analytics=True,
        analytics_width=1 << 10, analytics_stripe=1,
        analytics_drain_interval_s=0,   # manual drains: no racing
        analytics_top_k=4, analytics_scan_ports=16,
        analytics_hh_share=0.25))
    server = APIServer(d).start()
    base = f"http://127.0.0.1:{server.port}"
    hh_before = _count(EVENT_TRAFFIC_HEAVY_HITTER)
    scan_before = _count(EVENT_TRAFFIC_SCAN_SUSPECT)
    try:
        st = d.status()["analytics"]
        assert st["enabled"] and st["status"] == "ok"
        assert st["report"]["stripe"] == 1
        d.datapath.load_policy([_policy()], revision=1,
                               ipcache_prefixes={
                                   "10.0.0.0/8": HTTP_ID,
                                   "20.0.0.0/8": DNS_ID})
        d.datapath.set_endpoint_identity(0, EP_IDENTITY)

        def _drive(now):
            # identity 777: 64 big allowed HTTP frames (the hitter);
            # identity 888: a 40-dport egress sweep, denied (the scan)
            nh, ns = 64, 40
            hh = {
                "endpoint": np.zeros(nh, np.int32),
                "saddr": np.full(nh, (10 << 24) | 5, np.uint32)
                .view(np.int32),
                "daddr": np.full(nh, (10 << 24) | 8, np.uint32)
                .view(np.int32),
                "sport": (40000 + np.arange(nh)).astype(np.int32),
                "dport": np.full(nh, 80, np.int32),
                "proto": np.full(nh, 6, np.int32),
                "direction": np.zeros(nh, np.int32),
                "tcp_flags": np.full(nh, 0x02, np.int32),
                "length": np.full(nh, 1400, np.int32),
                "is_fragment": np.zeros(nh, np.int32),
            }
            sc = {
                "endpoint": np.zeros(ns, np.int32),
                "saddr": np.full(ns, (10 << 24) | 5, np.uint32)
                .view(np.int32),
                "daddr": np.full(ns, (20 << 24) | 9, np.uint32)
                .view(np.int32),
                "sport": np.full(ns, 51000, np.int32),
                "dport": (1 + np.arange(ns)).astype(np.int32),
                "proto": np.full(ns, 6, np.int32),
                "direction": np.ones(ns, np.int32),
                "tcp_flags": np.full(ns, 0x02, np.int32),
                "length": np.full(ns, 60, np.int32),
                "is_fragment": np.zeros(ns, np.int32),
            }
            d.datapath.process_packed(_stage_of(hh), now=now)
            d.datapath.process_packed(_stage_of(sc), now=now + 1)

        _drive(100)
        out = d.analytics_drain()
        assert out["status"] == "ok"
        assert out["top"][0]["identity"] == HTTP_ID
        assert DNS_ID in out["suspects"]
        assert ANALYTICS_TOP_BYTES.value(
            labels={"identity": str(HTTP_ID)}) == \
            out["top"][0]["count"]
        assert ANALYTICS_SCAN_SUSPECTS.value() >= 1
        assert _count(EVENT_TRAFFIC_HEAVY_HITTER) == hh_before + 1
        assert _count(EVENT_TRAFFIC_SCAN_SUSPECT) == scan_before + 1
        st = d.status()["analytics"]
        assert HTTP_ID in st["heavy-hitters"]
        assert DNS_ID in st["scan-suspects"]

        # sustained anomaly: the SAME offenders drain again — no
        # duplicate flight-recorder events (edge-triggered)
        _drive(200)
        out2 = d.analytics_drain()
        assert out2["top"][0]["identity"] == HTTP_ID
        assert _count(EVENT_TRAFFIC_HEAVY_HITTER) == hh_before + 1
        assert _count(EVENT_TRAFFIC_SCAN_SUSPECT) == scan_before + 1

        # REST reads the QUIESCED epoch swap-free
        c = Client(base)
        assert c.get("/analytics")["enabled"] is True
        got = c.get("/analytics/top?view=talkers&n=5&metric=bytes")
        assert got["partial"] is False
        assert got["entries"][0]["identity"] == HTTP_ID
        got2 = c.get("/analytics/top?view=scanners&n=5")
        assert any(e["identity"] == DNS_ID and e["suspect"]
                   for e in got2["entries"])

        # the CLI twin renders the same answers
        assert cli_main(["--api", base, "top", "talkers",
                         "-n", "5"]) == 0
        assert str(HTTP_ID) in capsys.readouterr().out
        assert cli_main(["--api", base, "top", "scanners"]) == 0
        cli_out = capsys.readouterr().out
        assert str(DNS_ID) in cli_out and "SCAN-SUSPECT" in cli_out

        # a quiet epoch: the capped gauge export zeroes the evicted
        # identities so the label set never grows under churn
        out3 = d.analytics_drain()
        assert out3["top"] == []
        assert ANALYTICS_TOP_BYTES.value(
            labels={"identity": str(HTTP_ID)}) == 0
        assert ANALYTICS_SCAN_SUSPECTS.value() == 0
    finally:
        server.shutdown()
        d.shutdown()


# ----------------------------------------------------- status shapes

def test_engine_report_and_disabled_status_shapes():
    dp = _engine(flows=False, threat=False, provenance=False)
    rep = dp.analytics_report()
    assert rep == {"width": WIDTH, "depth": DEPTH, "lanes": LANES,
                   "stripe": STRIPE, "shard": dp.shard_index,
                   "write-epoch": 0}
    dp.disable_analytics()
    assert dp.analytics_report() is None
    assert dp.analytics_snapshot() is None
    with pytest.raises(RuntimeError):
        dp.swap_analytics_epoch()
