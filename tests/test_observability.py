"""Runtime self-telemetry (cilium_tpu/observability/).

Covers the tracer (span trees, context propagation, fake clocks,
bounded buffer, disabled no-op), the policy-propagation latency
tracker, the map-pressure report, JIT/compile telemetry, the
pipeline-stage breakdown, full-registry Prometheus conformance
(every declared series exposed, histograms with zero observations
included), the three previously-dead metric wirings
(PROXY_UPSTREAM_TIME, KVSTORE_OPERATIONS, POLICY_VERDICTS), and the
live-daemon end-to-end acceptance path: insert rule -> the
policy_implementation_delay histogram increments and /debug/traces
shows the revision's span tree (import -> compile -> device apply ->
first verdict).
"""

import io
import json
import re
import sys
import threading
import time

import numpy as np
import pytest

from cilium_tpu.observability import (POLICY_IMPLEMENTATION_DELAY,
                                      PolicyPropagationTracker,
                                      compute_pressure, jit_telemetry,
                                      pipeline_report, record_stage)
from cilium_tpu.observability.tracer import NOOP_SPAN, Tracer
from cilium_tpu.utils.metrics import (KVSTORE_OPERATIONS,
                                      POLICY_VERDICTS,
                                      PROXY_UPSTREAM_TIME, Histogram,
                                      registry)


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


# ------------------------------------------------------------------ tracer

class TestTracer:
    def test_nested_spans_thread_local_parenting(self):
        clock = FakeClock()
        tr = Tracer(capacity=64, clock=clock)
        with tr.span("outer", attrs={"k": 1}) as outer:
            clock.advance(1.0)
            with tr.span("inner") as inner:
                clock.advance(0.5)
            assert inner.trace_id == outer.trace_id
            assert inner.parent_id == outer.span_id
        tree = tr.tree(outer.trace_id)
        assert tree["spans"][0]["name"] == "outer"
        assert tree["spans"][0]["children"][0]["name"] == "inner"
        assert tree["spans"][0]["duration-s"] == pytest.approx(1.5)
        assert tree["spans"][0]["children"][0]["duration-s"] == \
            pytest.approx(0.5)

    def test_explicit_parent_context_across_threads(self):
        tr = Tracer(capacity=64)
        with tr.span("root") as root:
            ctx = root.context
        done = threading.Event()

        def worker():
            tr.span("child-on-other-thread", parent=ctx).finish()
            done.set()

        threading.Thread(target=worker).start()
        assert done.wait(5)
        tree = tr.tree(ctx.trace_id)
        names = [c["name"] for c in tree["spans"][0]["children"]]
        assert "child-on-other-thread" in names

    def test_disabled_is_noop(self):
        tr = Tracer(enabled=False)
        span = tr.span("nope")
        assert span is NOOP_SPAN
        with span:
            pass
        assert tr.snapshot() == []
        assert tr.child_span("also-nope") is NOOP_SPAN

    def test_child_span_requires_active_trace(self):
        tr = Tracer()
        assert tr.child_span("orphan") is NOOP_SPAN
        with tr.span("parent"):
            child = tr.child_span("kv-op")
            assert child is not NOOP_SPAN
            child.finish()

    def test_bounded_ring_evicts_and_counts(self):
        tr = Tracer(capacity=8)
        for i in range(20):
            tr.span(f"s{i}", root=True).finish()
        assert len(tr.snapshot()) == 8
        assert tr.dropped == 12
        # newest survive
        assert tr.snapshot()[-1]["name"] == "s19"

    def test_trace_summaries_and_find(self):
        tr = Tracer(capacity=64)
        with tr.span("alpha", attrs={"revision": 7}):
            with tr.span("beta"):
                pass
        summaries = tr.traces()
        assert summaries[-1]["root"] == "alpha"
        assert summaries[-1]["spans"] == 2
        assert tr.find_trace(revision=7) == summaries[-1]["trace-id"]
        assert tr.find_trace(revision=12345) is None

    def test_error_status_on_exception(self):
        tr = Tracer(capacity=8)
        with pytest.raises(ValueError):
            with tr.span("boom"):
                raise ValueError("x")
        assert tr.snapshot()[-1]["status"] == "error"

    def test_configure_capacity_preserves_spans(self):
        tr = Tracer(capacity=4)
        for i in range(4):
            tr.span(f"s{i}", root=True).finish()
        tr.configure(capacity=16)
        assert len(tr.snapshot()) == 4
        assert tr.capacity == 16


# ------------------------------------------------------- propagation latency

class TestPropagationTracker:
    def _tracker(self):
        clock = FakeClock()
        tr = Tracer(capacity=256, clock=clock)
        return PolicyPropagationTracker(tracer=tr, clock=clock), \
            tr, clock

    def test_full_journey_observes_histogram(self):
        tracker, tr, clock = self._tracker()
        before = POLICY_IMPLEMENTATION_DELAY.total_count()
        tracker.revision_imported(5, rules=3, import_seconds=0.01)
        clock.advance(0.2)
        with tracker.stage_span(5, "policy.compile", {"endpoint": 1}):
            clock.advance(0.1)
        tracker.revision_compiled(5)
        with tracker.stage_span(5, "policy.device-apply"):
            clock.advance(0.05)
        tracker.revision_applied(5)
        clock.advance(0.15)
        tracker.revision_served(5)
        assert POLICY_IMPLEMENTATION_DELAY.total_count() == before + 1
        rec = tracker.report(1)[0]
        assert rec["revision"] == 5
        assert rec["first-verdict-delay-s"] == pytest.approx(0.51)
        assert rec["compile-delay-s"] == pytest.approx(0.31)
        assert rec["device-apply-delay-s"] == pytest.approx(0.36)
        # span tree: import is the root, stages + first-verdict nest
        tree = tr.tree(tracker.trace_id_of(5))
        root = tree["spans"][0]
        assert root["name"].startswith("policy.import")
        child_names = [c["name"] for c in root["children"]]
        assert any(n == "policy.compile" for n in child_names)
        assert any(n == "policy.device-apply" for n in child_names)
        assert any(n.startswith("policy.first-verdict")
                   for n in child_names)

    def test_superseded_revisions_complete_together(self):
        tracker, _tr, clock = self._tracker()
        before = POLICY_IMPLEMENTATION_DELAY.total_count()
        tracker.revision_imported(2)
        clock.advance(1.0)
        tracker.revision_imported(3)
        clock.advance(1.0)
        tracker.revision_served(3)
        # both pending revisions closed by the one serving dispatch
        assert POLICY_IMPLEMENTATION_DELAY.total_count() == before + 2
        recs = {r["revision"]: r for r in tracker.report()}
        assert recs[2]["first-verdict-delay-s"] == pytest.approx(2.0)
        assert recs[3]["first-verdict-delay-s"] == pytest.approx(1.0)

    def test_served_is_monotonic_and_idempotent(self):
        tracker, _tr, clock = self._tracker()
        before = POLICY_IMPLEMENTATION_DELAY.total_count()
        tracker.revision_imported(4)
        tracker.revision_served(4)
        tracker.revision_served(4)  # repeat: no double count
        tracker.revision_served(3)  # stale: ignored
        assert POLICY_IMPLEMENTATION_DELAY.total_count() == before + 1

    def test_history_bounded(self):
        tracker, _tr, _clock = self._tracker()
        tracker.capacity = 4
        for rev in range(10, 30):
            tracker.revision_imported(rev)
        assert len(tracker.report(100)) == 4
        assert tracker.report(100)[-1]["revision"] == 29


# ------------------------------------------- histogram zero-observation fix

class TestHistogramZeroObservations:
    def test_declared_histogram_exposes_zero_series(self):
        h = Histogram("cilium_tpu_test_empty_hist", "empty",
                      buckets=(0.1, 1.0))
        lines = h.expose()
        assert "cilium_tpu_test_empty_hist_sum 0.0" in lines
        assert "cilium_tpu_test_empty_hist_count 0" in lines
        inf = [l for l in lines if 'le="+Inf"' in l]
        assert inf == ['cilium_tpu_test_empty_hist_bucket'
                       '{le="+Inf"} 0']
        # one line per bucket + inf + sum + count
        assert len(lines) == 2 + 3

    def test_observation_replaces_zero_series(self):
        h = Histogram("cilium_tpu_test_one_hist", "one",
                      buckets=(0.1, 1.0))
        h.observe(0.05)
        lines = h.expose()
        assert "cilium_tpu_test_one_hist_count 1" in lines
        # the synthetic empty series is gone
        assert lines.count("cilium_tpu_test_one_hist_count 1") == 1
        assert h.count() == 1 and h.sum_value() == pytest.approx(0.05)


class TestObserveManyEdgeCases:
    """The batched-ingest path (observe_many) at its boundaries: a
    zero-count call, negative observation values, numpy-integer
    counts, and the monitor's per-score path fed an empty batch —
    each must keep the exposition Prometheus-conformant."""

    def test_count_zero_is_a_noop_on_every_series(self):
        h = Histogram("cilium_tpu_test_many_zero", "zc",
                      buckets=(0.1, 1.0))
        h.observe_many(0.5, 0)
        assert h.count() == 0
        assert h.sum_value() == 0.0
        lines = h.expose()
        assert "cilium_tpu_test_many_zero_count 0" in lines
        assert "cilium_tpu_test_many_zero_sum 0.0" in lines
        assert 'cilium_tpu_test_many_zero_bucket{le="+Inf"} 0' \
            in lines
        # still the full declared series, nothing duplicated
        assert len(lines) == 2 + 3

    def test_negative_values_bucket_cumulatively(self):
        h = Histogram("cilium_tpu_test_many_neg", "neg",
                      buckets=(0.1, 1.0))
        h.observe_many(-2.0, 3)
        # a negative observation lands in EVERY bucket (cumulative
        # le-semantics) and drives _sum negative — never a lost count
        assert h.count() == 3
        assert h.sum_value() == pytest.approx(-6.0)
        lines = h.expose()
        assert 'cilium_tpu_test_many_neg_bucket{le="0.1"} 3' in lines
        assert 'cilium_tpu_test_many_neg_bucket{le="+Inf"} 3' in lines
        # bucket counts stay monotonically non-decreasing in le order
        counts = [int(l.rsplit(" ", 1)[1]) for l in lines
                  if "_bucket" in l]
        assert counts == sorted(counts)

    def test_numpy_integer_counts_coerce(self):
        import numpy as np
        h = Histogram("cilium_tpu_test_many_np", "np",
                      buckets=(0.1, 1.0))
        h.observe_many(0.05, np.int64(4))
        h.observe_many(0.5, np.int32(2))
        assert h.count() == 6
        assert isinstance(h.count(), int)
        assert h.sum_value() == pytest.approx(0.05 * 4 + 0.5 * 2)

    def test_monitor_per_score_path_with_empty_batch(self):
        import numpy as np
        from cilium_tpu.monitor import MonitorHub
        from cilium_tpu.utils.metrics import (THREAT_SCORES,
                                              THREAT_VERDICTS)
        hub = MonitorHub()
        empty = np.zeros(0, dtype=np.int32)
        scores_before = THREAT_SCORES.total_count()
        verdicts_before = THREAT_VERDICTS.total()
        # an empty batch with the threat lane attached must be a
        # clean no-op: no samples, no counters, no exceptions
        hub.ingest_batch(empty, empty, empty, empty, empty, empty,
                         tiers=empty, match_slots=empty,
                         threat_out=empty)
        assert THREAT_SCORES.total_count() == scores_before
        assert THREAT_VERDICTS.total() == verdicts_before
        assert hub.tail(10) == []
        assert hub.lost == 0
        assert hub.top_dropped_rules() == []


# ------------------------------------------------- registry-wide conformance

def _parse_metrics(text):
    """Parse exposition text -> (helps, types, samples)."""
    helps, types, samples = {}, {}, []
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            name, _, rest = line[len("# HELP "):].partition(" ")
            helps[name] = rest
        elif line.startswith("# TYPE "):
            name, _, kind = line[len("# TYPE "):].partition(" ")
            types[name] = kind
        else:
            m = re.fullmatch(
                r"([a-zA-Z_:][a-zA-Z0-9_:]*)"
                r"(\{.*\})? ([0-9eE+.\-]+|NaN)", line)
            assert m, f"unparseable sample line: {line!r}"
            samples.append((m.group(1), m.group(2) or "",
                            m.group(3)))
    return helps, types, samples


class TestPrometheusConformance:
    def test_full_registry_exposition(self):
        text = registry.expose_text()
        helps, types, samples = _parse_metrics(text)
        # every registered metric has HELP and TYPE
        with registry._lock:
            metrics = dict(registry._metrics)
        for name, metric in metrics.items():
            assert types.get(name) == metric.kind, name
            assert name in helps and helps[name], \
                f"{name} missing HELP"
        # no duplicate series (name + labelset unique)
        seen = set()
        for name, labels, _v in samples:
            assert (name, labels) not in seen, \
                f"duplicate series {name}{labels}"
            seen.add((name, labels))
        # histograms expose _sum/_count (+Inf bucket) per declared
        # metric, observations or not
        sample_names = {s[0] for s in samples}
        for name, metric in metrics.items():
            if metric.kind == "histogram":
                assert f"{name}_sum" in sample_names, name
                assert f"{name}_count" in sample_names, name
                assert any(n == f"{name}_bucket" and 'le="+Inf"' in l
                           for n, l, _ in samples), name
            else:
                assert name in sample_names, \
                    f"{name} declared but exposes no samples"

    def test_every_metric_has_help_text(self):
        with registry._lock:
            metrics = list(registry._metrics.values())
        missing = [m.name for m in metrics if not m.help]
        assert not missing, f"metrics without help text: {missing}"


# --------------------------------------------------------------- map pressure

class TestMapPressure:
    def test_compute_pressure_warnings(self):
        inventory = {
            "ct": {"slots": 100, "occupied": 95, "max-probe": 8},
            "ct6": {"slots": 100, "occupied": 10, "max-probe": 8},
            "policy": {"endpoints": 8, "slots": 64, "attached": 8},
            "hubble-flows": {"slots": 64, "occupied": 32},
            "ipcache": {"entries": 12},
            "lb": {"services": 3},
        }
        report = compute_pressure(inventory, warn_threshold=0.9)
        maps = report["maps"]
        assert maps["ct"]["pressure"] == pytest.approx(0.95)
        assert maps["ct6"]["pressure"] == pytest.approx(0.10)
        assert maps["policy-rows"]["pressure"] == pytest.approx(1.0)
        assert maps["hubble-flows"]["pressure"] == pytest.approx(0.5)
        assert maps["ipcache"]["pressure"] is None
        warn_maps = [w.split(":")[0] for w in report["warnings"]]
        assert set(warn_maps) == {"ct", "policy-rows"}
        # gauges updated in lockstep with the report
        from cilium_tpu.observability import MAP_PRESSURE
        assert MAP_PRESSURE.value(labels={"map": "ct"}) == \
            pytest.approx(0.95)

    def test_live_engine_pressure(self):
        from cilium_tpu.datapath.engine import Datapath
        from cilium_tpu.policy.mapstate import PolicyMapState
        dp = Datapath(ct_slots=1 << 8)
        dp.load_policy([PolicyMapState()], revision=1,
                       ipcache_prefixes={"10.0.0.0/8": 2})
        report = dp.map_pressure()
        assert report["maps"]["ct"]["capacity"] == 1 << 8
        assert report["maps"]["ct"]["pressure"] == 0.0
        assert report["warnings"] == []


# ------------------------------------------------------------ jit telemetry

class TestJitTelemetry:
    def test_hit_miss_classification(self):
        from cilium_tpu.observability.jitstats import JitTelemetry
        t = JitTelemetry()
        assert t.record("step", 1, 256, 1.5) is True    # compile
        assert t.record("step", 1, 256, 0.001) is False  # hit
        assert t.record("step", 1, 512, 1.2) is True    # new shape
        assert t.record("step", 2, 256, 1.0) is True    # new program
        rep = t.report()
        assert rep["compiles"]["step"] == 3
        assert rep["cache-hits"] == 1 and rep["cache-misses"] == 3
        assert rep["compile-seconds"]["step"] == pytest.approx(3.7)

    def test_disabled_records_nothing(self):
        from cilium_tpu.observability.jitstats import JitTelemetry
        t = JitTelemetry()
        t.enabled = False
        assert t.record("step", 1, 256, 1.5) is False
        assert t.report()["cache-misses"] == 0

    def test_engine_accounts_compiles_and_hits(self):
        from cilium_tpu.datapath.engine import Datapath, \
            make_full_batch
        from cilium_tpu.policy.mapstate import PolicyMapState
        before = jit_telemetry.report()
        dp = Datapath(ct_slots=1 << 8)
        dp.load_policy([PolicyMapState()], revision=1,
                       ipcache_prefixes={})
        pkt = make_full_batch(endpoint=[0], saddr=[1], daddr=[2],
                              sport=[1], dport=[80])
        dp.process(pkt, now=10)
        dp.process(pkt, now=11)
        after = jit_telemetry.report()
        assert after["cache-misses"] >= before["cache-misses"] + 1
        assert after["cache-hits"] >= before["cache-hits"] + 1
        assert after["compiles"].get("datapath.process", 0) >= \
            before["compiles"].get("datapath.process", 0) + 1
        assert after["device-bytes"].get("engine-tables", 0) > 0

    def test_engine_telemetry_disabled_is_silent(self):
        from cilium_tpu.datapath.engine import Datapath, \
            make_full_batch
        from cilium_tpu.policy.mapstate import PolicyMapState
        dp = Datapath(ct_slots=1 << 8)
        dp.telemetry_enabled = False
        dp.load_policy([PolicyMapState()], revision=1,
                       ipcache_prefixes={})
        before = jit_telemetry.report()
        pkt = make_full_batch(endpoint=[0], saddr=[1], daddr=[2],
                              sport=[1], dport=[80])
        dp.process(pkt, now=10)
        after = jit_telemetry.report()
        assert after["cache-misses"] == before["cache-misses"]
        assert not dp._pending_verdicts


# ------------------------------------------------------------ pipeline stages

class TestPipelineStages:
    def test_report_shares_and_blocking_flags(self):
        record_stage("test-family", "pack", 0.001)
        record_stage("test-family", "pack", 0.003)
        record_stage("test-family", "sync", 0.006)
        rep = pipeline_report()["test-family"]
        assert rep["pack"]["count"] >= 2
        assert rep["sync"]["blocking-boundary"] is True
        assert rep["pack"]["blocking-boundary"] is False
        total = sum(s["share-pct"] for s in rep.values())
        assert total == pytest.approx(100.0, abs=0.5)

    def test_histogram_series_exported(self):
        record_stage("test-family2", "dispatch", 0.002)
        text = registry.expose_text()
        assert 'cilium_tpu_pipeline_stage_seconds_count' \
            '{family="test-family2",stage="dispatch"}' in text


# ----------------------------------------------- previously-dead metric wires

class TestWiredMetrics:
    def test_policy_verdicts_from_engine_path(self):
        from cilium_tpu.datapath.engine import Datapath, \
            make_full_batch
        from cilium_tpu.policy.mapstate import (EGRESS, PolicyKey,
                                                PolicyMapState,
                                                PolicyMapStateEntry)
        st = PolicyMapState({
            PolicyKey(identity=2, dest_port=80, nexthdr=6,
                      direction=EGRESS): PolicyMapStateEntry()})
        dp = Datapath(ct_slots=1 << 8)
        dp.load_policy([st], revision=1,
                       ipcache_prefixes={"0.0.0.0/0": 2})
        allowed0 = POLICY_VERDICTS.value(
            labels={"outcome": "allowed"})
        denied0 = POLICY_VERDICTS.value(labels={"outcome": "denied"})
        pkt = make_full_batch(endpoint=[0, 0], saddr=[1, 1],
                              daddr=[2, 2], sport=[999, 999],
                              dport=[80, 22])
        dp.process(pkt, now=10)
        dp.flush_telemetry()
        assert POLICY_VERDICTS.value(
            labels={"outcome": "allowed"}) == allowed0 + 1
        assert POLICY_VERDICTS.value(
            labels={"outcome": "denied"}) == denied0 + 1

    def test_kvstore_operations_counted(self):
        from cilium_tpu.kvstore.remote import RemoteBackend
        from cilium_tpu.kvstore.server import KVStoreServer
        srv = KVStoreServer(port=0).start()
        try:
            kv = RemoteBackend(port=srv.port)
            set0 = KVSTORE_OPERATIONS.value(
                labels={"backend": "remote", "op": "set"})
            get0 = KVSTORE_OPERATIONS.value(
                labels={"backend": "remote", "op": "get"})
            kv.set("a/b", b"1")
            kv.get("a/b")
            kv.get("a/missing")
            assert KVSTORE_OPERATIONS.value(
                labels={"backend": "remote", "op": "set"}) == set0 + 1
            assert KVSTORE_OPERATIONS.value(
                labels={"backend": "remote", "op": "get"}) == get0 + 2
            kv.close()
        finally:
            srv.shutdown()

    def test_etcd_operations_counted(self):
        from cilium_tpu.kvstore.etcd import EtcdBackend
        from cilium_tpu.kvstore.mini_etcd import MiniEtcd
        mini = MiniEtcd().start()
        try:
            kv = EtcdBackend(port=mini.port, lease_ttl=5)
            put0 = KVSTORE_OPERATIONS.value(
                labels={"backend": "etcd", "op": "kv-put"})
            rng0 = KVSTORE_OPERATIONS.value(
                labels={"backend": "etcd", "op": "kv-range"})
            kv.set("x", b"y")
            kv.get("x")
            assert KVSTORE_OPERATIONS.value(
                labels={"backend": "etcd", "op": "kv-put"}) == put0 + 1
            assert KVSTORE_OPERATIONS.value(
                labels={"backend": "etcd",
                        "op": "kv-range"}) >= rng0 + 1
            kv.close()
        finally:
            mini.shutdown()

    def test_proxy_upstream_time_http(self):
        import socket
        import socketserver
        from cilium_tpu.l7.socket_proxy import (ListenerContext,
                                                SocketProxy)

        ok = (b"HTTP/1.1 200 OK\r\ncontent-length: 2\r\n\r\nhi")

        class _Up(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        class _H(socketserver.BaseRequestHandler):
            def handle(self):
                data = b""
                while b"\r\n\r\n" not in data:
                    chunk = self.request.recv(4096)
                    if not chunk:
                        return
                    data += chunk
                self.request.sendall(ok)

        up = _Up(("127.0.0.1", 0), _H)
        threading.Thread(target=up.serve_forever, daemon=True).start()
        proxy = SocketProxy()
        before = PROXY_UPSTREAM_TIME.count(
            labels={"protocol": "http"})
        try:
            port = proxy.start_listener(0, ListenerContext(
                redirect_id="r1", parser_type="http",
                orig_dst=lambda peer: ("127.0.0.1",
                                       up.server_address[1])))
            with socket.create_connection(("127.0.0.1", port),
                                          timeout=5) as s:
                s.sendall(b"GET / HTTP/1.1\r\nhost: a\r\n"
                          b"content-length: 0\r\n\r\n")
                resp = b""
                s.settimeout(5)
                while b"hi" not in resp:
                    chunk = s.recv(4096)
                    if not chunk:
                        break
                    resp += chunk
            assert b"200 OK" in resp
            deadline = time.time() + 5
            while time.time() < deadline and \
                    PROXY_UPSTREAM_TIME.count(
                        labels={"protocol": "http"}) == before:
                time.sleep(0.02)
            assert PROXY_UPSTREAM_TIME.count(
                labels={"protocol": "http"}) == before + 1
            assert PROXY_UPSTREAM_TIME.sum_value(
                labels={"protocol": "http"}) >= 0.0
        finally:
            proxy.shutdown()
            up.shutdown()
            up.server_close()


# ------------------------------------------------------- live-daemon e2e

@pytest.fixture
def agent(tmp_path):
    from cilium_tpu.daemon import Daemon
    from cilium_tpu.daemon.rest import APIServer
    from cilium_tpu.utils.option import DaemonConfig
    d = Daemon(config=DaemonConfig(state_dir=""), builders=2)
    server = APIServer(d).start()
    yield d, server
    server.shutdown()
    d.shutdown()


RULES = [{
    "endpointSelector": {"matchLabels": {"id": "server"}},
    "ingress": [{
        "fromEndpoints": [{"matchLabels": {"id": "client"}}],
        "toPorts": [{"ports": [{"port": "80",
                                "protocol": "TCP"}]}]}],
    "labels": ["k8s:policy=obs-e2e"],
}]


def _get(server, path):
    import urllib.request
    with urllib.request.urlopen(server.base_url + path,
                                timeout=10) as r:
        return json.loads(r.read())


def _cli(server, *argv):
    from cilium_tpu.cli import main as cli_main
    out = io.StringIO()
    old = sys.stdout
    sys.stdout = out
    try:
        rc = cli_main(["--api", server.base_url, *argv])
    finally:
        sys.stdout = old
    return rc, out.getvalue()


class TestDaemonEndToEnd:
    def test_propagation_delay_and_trace_tree(self, agent):
        from cilium_tpu.datapath.engine import make_full_batch
        from cilium_tpu.policy.jsonio import rules_from_json
        d, server = agent
        d.endpoint_create(1, ipv4="10.200.0.21",
                          labels=["k8s:id=server"])
        d.endpoint_create(2, ipv4="10.200.0.22",
                          labels=["k8s:id=client"])
        before = POLICY_IMPLEMENTATION_DELAY.total_count()
        rev = d.policy_add(rules_from_json(json.dumps(RULES)))
        assert d.wait_for_policy_revision(rev)
        # no verdicts yet: the journey is still open
        assert POLICY_IMPLEMENTATION_DELAY.total_count() == before
        ep = d.endpoints.lookup(1)
        batch = make_full_batch(
            endpoint=[ep.table_slot], saddr=["10.200.0.22"],
            daddr=["10.200.0.21"], sport=[44000], dport=[80],
            direction=[0])
        verdict, _e, _i, _n = d.datapath.process(batch)
        verdict.block_until_ready()
        # acceptance: histogram count increments ...
        assert POLICY_IMPLEMENTATION_DELAY.total_count() == \
            before + 1
        # ... and /debug/traces shows the revision's span tree:
        # import -> compile -> device apply -> first verdict
        tree = _get(server, f"/debug/traces?revision={rev}")
        root = tree["spans"][0]
        assert root["name"] == f"policy.import rev={rev}"
        child_names = [c["name"] for c in root["children"]]
        assert "policy.compile" in child_names
        assert "policy.device-apply" in child_names
        assert f"policy.first-verdict rev={rev}" in child_names
        # compile happened before device-apply in the tree ordering
        assert child_names.index("policy.compile") < \
            child_names.index("policy.device-apply")
        # the delay is also in /metrics via REST
        text = _get_raw(server, "/metrics")
        assert "policy_implementation_delay_seconds_count" in text
        # the summaries list includes this trace
        summary = _get(server, "/debug/traces")
        assert any(t["trace-id"] == tree["trace-id"]
                   for t in summary["traces"])
        assert any(r["revision"] == rev
                   for r in summary["propagation"])

    def test_debug_pipeline_and_status_surfaces(self, agent):
        from cilium_tpu.datapath.engine import make_full_batch
        d, server = agent
        d.endpoint_create(1, ipv4="10.200.0.31",
                          labels=["k8s:id=a"])
        ep = d.endpoints.lookup(1)
        batch = make_full_batch(endpoint=[ep.table_slot],
                                saddr=["10.200.0.32"],
                                daddr=["10.200.0.31"], sport=[1],
                                dport=[80], direction=[0])
        d.datapath.process(batch)
        rep = _get(server, "/debug/pipeline")
        assert "engine-v4" in rep
        assert "dispatch" in rep["engine-v4"]
        st = _get(server, "/healthz")
        assert "map-pressure" in st
        assert "ct" in st["map-pressure"]["maps"]
        assert st["telemetry"]["tracing"]["enabled"] is True
        assert "cache-misses" in st["telemetry"]["jit"]
        # CLI surfaces
        rc, out = _cli(server, "status", "--verbose")
        assert rc == 0
        assert "JIT:" in out and "Tracing:" in out
        rc, out = _cli(server, "trace")
        assert rc == 0 and "TRACE" in out

    def test_cli_trace_tree_by_revision(self, agent):
        from cilium_tpu.policy.jsonio import rules_from_json
        d, server = agent
        d.endpoint_create(1, ipv4="10.200.0.41",
                          labels=["k8s:id=server"])
        rev = d.policy_add(rules_from_json(json.dumps(RULES)))
        assert d.wait_for_policy_revision(rev)
        rc, out = _cli(server, "trace", "--revision", str(rev))
        assert rc == 0
        assert f"policy.import rev={rev}" in out
        assert "policy.compile" in out
        # unknown revision: 404 surfaces as the CLI's typed APIError
        from cilium_tpu.cli import APIError
        with pytest.raises(APIError) as exc:
            _cli(server, "trace", "--revision", "99999")
        assert exc.value.status == 404

    def test_bugtool_contains_observability_members(self, agent,
                                                    tmp_path):
        import tarfile
        from cilium_tpu.bugtool import collect
        d, _server = agent
        path = collect(d, str(tmp_path / "bt.tar.gz"))
        with tarfile.open(path) as tar:
            names = [n.split("/", 1)[1] for n in tar.getnames()]
        for member in ("traces.json", "map-pressure.json",
                       "compile-telemetry.json", "pipeline.json"):
            assert member in names, names

    def test_tracing_disabled_config(self, tmp_path):
        from cilium_tpu.daemon import Daemon
        from cilium_tpu.utils.option import DaemonConfig
        d = Daemon(config=DaemonConfig(state_dir="",
                                       enable_tracing=False))
        try:
            assert d.datapath.telemetry_enabled is False
            assert d.tracer.enabled is False
            st = d.status()
            assert st["telemetry"]["tracing"]["enabled"] is False
        finally:
            d.shutdown()
            # the tracer is process-global: re-enable for the rest of
            # the test session
            d.tracer.configure(enabled=True)


def _get_raw(server, path):
    import urllib.request
    with urllib.request.urlopen(server.base_url + path,
                                timeout=10) as r:
        return r.read().decode()
