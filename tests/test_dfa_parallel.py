"""Sequence-parallel DFA: associative-scan and shard_map paths must
agree exactly with the serial scan (dfa_ops.dfa_scan) and the Python
regex oracle.
"""

import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cilium_tpu.compiler.regexc import compile_regex_set
from cilium_tpu.ops.dfa_ops import dfa_match, dfa_scan, encode_strings
from cilium_tpu.ops.dfa_parallel import (compose, dfa_match_parallel,
                                         dfa_parallel_scan,
                                         dfa_scan_sharded,
                                         transition_functions)
from cilium_tpu.parallel.mesh import make_mesh


REGEXES = ["GET", "/public.*", "/api/v[0-9]+/.*", ".*admin.*", "POST|PUT"]
INPUTS = ["GET", "/public/index.html", "/api/v2/users", "/admin/x",
          "PUT", "DELETE", "/api/vX/users", "/public", "xadminy", ""]


@pytest.fixture(scope="module")
def compiled():
    return compile_regex_set(REGEXES)


def test_parallel_matches_serial_and_oracle(compiled):
    table = jnp.asarray(compiled.table)
    accept = jnp.asarray(compiled.accept)
    starts = jnp.asarray(compiled.starts)
    data = jnp.asarray(encode_strings(INPUTS, 32))
    serial = np.asarray(dfa_match(table, accept, starts, data))
    par = np.asarray(dfa_match_parallel(table, accept, starts, data))
    np.testing.assert_array_equal(serial, par)
    for i, s in enumerate(INPUTS):
        for j, rx in enumerate(REGEXES):
            want = re.fullmatch(rx, s) is not None
            assert bool(par[i, j]) == want, (s, rx)


def test_compose_is_function_composition(compiled):
    rng = np.random.default_rng(0)
    s = compiled.table.shape[0]
    f = jnp.asarray(rng.integers(0, s, (4, s)).astype(np.int32))
    g = jnp.asarray(rng.integers(0, s, (4, s)).astype(np.int32))
    h = np.asarray(compose(g, f))
    for b in range(4):
        for st in range(s):
            assert h[b, st] == int(g[b, int(f[b, st])])


def test_parallel_scan_carries_state_like_serial(compiled):
    """Chunked evaluation: state carried across chunk boundaries."""
    table = jnp.asarray(compiled.table)
    starts = jnp.asarray(compiled.starts)
    full = encode_strings(INPUTS, 32)
    b = full.shape[0]
    states = jnp.broadcast_to(starts[None, :],
                              (b, starts.shape[0])).astype(jnp.int32)
    # serial over the whole payload
    ref = np.asarray(dfa_scan(table, states, jnp.asarray(full)))
    # parallel in two chunks of 16, carrying the state between
    st = dfa_parallel_scan(table, states, jnp.asarray(full[:, :16]))
    st = dfa_parallel_scan(table, st, jnp.asarray(full[:, 16:]))
    np.testing.assert_array_equal(ref, np.asarray(st))


def test_transition_functions_identity_on_padding(compiled):
    table = jnp.asarray(compiled.table)
    data = jnp.asarray(np.array([[-1, -1]], np.int32))
    f = np.asarray(transition_functions(table, data))
    s = compiled.table.shape[0]
    np.testing.assert_array_equal(f[0, 0], np.arange(s))
    np.testing.assert_array_equal(f[0, 1], np.arange(s))


def test_sharded_sequence_scan_all_devices(compiled):
    """Context parallelism: sequence axis sharded over all 8 virtual
    devices must agree with the serial scan."""
    n = len(jax.devices())
    mesh = make_mesh(n)  # (dp, ep) with ep=1; use dp as the seq axis
    table = jnp.asarray(compiled.table)
    starts = jnp.asarray(compiled.starts)
    seq_len = 16 * n
    long_inputs = ["/api/v2/" + "x" * 100, "/public/" + "y" * 40,
                   "no-match" * 12, "GET"]
    data = encode_strings(long_inputs, seq_len)
    b = data.shape[0]
    states = jnp.broadcast_to(starts[None, :],
                              (b, starts.shape[0])).astype(jnp.int32)
    ref = np.asarray(dfa_scan(table, states, jnp.asarray(data)))
    got = np.asarray(dfa_scan_sharded(table, states, jnp.asarray(data),
                                      mesh, "dp"))
    np.testing.assert_array_equal(ref, got)
    # accept verdicts line up with the regex oracle on the long rows
    accept = np.asarray(compiled.accept)
    ok = accept[got]
    for i, s in enumerate(long_inputs):
        for j, rx in enumerate(REGEXES):
            want = re.fullmatch(rx, s) is not None
            assert bool(ok[i, j]) == want, (s[:20], rx)
