"""Regex->DFA compiler tests: differential against Python re.fullmatch
(the policygen-style oracle matrix for the L7 compiler)."""

import re

import numpy as np
import pytest
import jax.numpy as jnp

from cilium_tpu.compiler.regexc import (RegexCompileError, compile_regex_set,
                                        oracle_match)
from cilium_tpu.ops.dfa_ops import dfa_match, dfa_scan, encode_strings

PATTERNS = [
    "GET",
    "GET|POST|PUT",
    "/public/.*",
    "/api/v[0-9]+/users/[0-9]+",
    "foo.?bar",
    "a+b*c?",
    "[a-zA-Z_][a-zA-Z0-9_]*",
    "(ab|cd)+x",
    "[^/]+/[^/]+",
    ".*\\.cilium\\.io",
    "a{2,4}",
    "x{3}y",
    "\\d+\\.\\d+",
    "(GET|HEAD)( /[a-z]*)?",
]

TEXTS = [
    "GET", "POST", "PUT", "PATCH", "get",
    "/public/index.html", "/public/", "/private/x",
    "/api/v1/users/42", "/api/v12/users/7", "/api/v/users/7",
    "foobar", "fooxbar", "fooxxbar",
    "abc", "aabbcc", "ac", "c", "",
    "hello_world", "9bad", "_ok",
    "abx", "cdx", "ababx", "abcdx", "x",
    "foo/bar", "a/b/c",
    "sub.cilium.io", "cilium.io", "evil.com",
    "aa", "aaa", "aaaa", "aaaaa",
    "xxxy", "xxy",
    "1.5", "12.34", "1,5",
    "GET /abc", "HEAD", "GET /ABC",
]


def test_dfa_differential_vs_re():
    compiled = compile_regex_set(PATTERNS)
    data = jnp.asarray(encode_strings(TEXTS, 64))
    got = np.asarray(dfa_match(jnp.asarray(compiled.table),
                               jnp.asarray(compiled.accept),
                               jnp.asarray(compiled.starts), data))
    for ti, text in enumerate(TEXTS):
        for pi, pat in enumerate(PATTERNS):
            want = re.fullmatch(pat, text) is not None
            assert got[ti, pi] == want, (pat, text, bool(got[ti, pi]), want)


def test_dfa_streaming_chunks_match_oneshot():
    """State carried across chunk boundaries must equal one-shot eval —
    the blockwise sequence dimension."""
    compiled = compile_regex_set(["/api/v[0-9]+/.*", "GET|PUT"])
    texts = ["/api/v42/some/long/path/xyz", "GET", "/api/vv/x"]
    L = 32
    data = encode_strings(texts, L)
    one = np.asarray(dfa_match(jnp.asarray(compiled.table),
                               jnp.asarray(compiled.accept),
                               jnp.asarray(compiled.starts),
                               jnp.asarray(data)))
    # chunked: 4 chunks of 8 bytes
    states = jnp.broadcast_to(
        jnp.asarray(compiled.starts)[None, :],
        (len(texts), compiled.starts.shape[0])).astype(jnp.int32)
    for c in range(0, L, 8):
        states = dfa_scan(jnp.asarray(compiled.table), states,
                          jnp.asarray(data[:, c:c + 8]))
    chunked = np.asarray(jnp.asarray(compiled.accept)[states])
    np.testing.assert_array_equal(one, chunked)


def test_unsupported_constructs_rejected():
    with pytest.raises(RegexCompileError):
        compile_regex_set([r"(?=look)ahead"])
    with pytest.raises(RegexCompileError):
        compile_regex_set([r"(a)\1"])


def test_state_budget_enforced():
    with pytest.raises(RegexCompileError):
        compile_regex_set(["(a|b){40}" * 8], max_states=64)


def test_overlong_input_never_matches():
    compiled = compile_regex_set([".*"])
    data = jnp.asarray(encode_strings(["x" * 100], 8))
    got = np.asarray(dfa_match(jnp.asarray(compiled.table),
                               jnp.asarray(compiled.accept),
                               jnp.asarray(compiled.starts), data))
    assert not got.any()
