"""k8s policy parsing, ToServices translation, and watcher-driven agent.

Mirrors pkg/k8s tests: CNP parse fixtures (network_policy.go tests),
namespace scoping, NetworkPolicy peers, rule_translate.
"""

import numpy as np
import pytest

from cilium_tpu.daemon import Daemon
from cilium_tpu.k8s import (K8sWatcher, parse_cnp, parse_network_policy,
                            translate_to_services)
from cilium_tpu.labels import LabelArray
from cilium_tpu.policy.api import PolicyError, Rule
from cilium_tpu.policy.repository import Repository
from cilium_tpu.policy.trace import SearchContext
from cilium_tpu.utils.option import DaemonConfig

CNP = {
    "apiVersion": "cilium.io/v2",
    "kind": "CiliumNetworkPolicy",
    "metadata": {"name": "web-policy", "namespace": "prod"},
    "spec": {
        "endpointSelector": {"matchLabels": {"app": "web"}},
        "ingress": [
            {"fromEndpoints": [{"matchLabels": {"app": "client"}}],
             "toPorts": [{"ports": [{"port": "80", "protocol": "TCP"}]}]},
        ],
    },
}

NP = {
    "apiVersion": "networking.k8s.io/v1",
    "kind": "NetworkPolicy",
    "metadata": {"name": "db-np", "namespace": "prod"},
    "spec": {
        "podSelector": {"matchLabels": {"role": "db"}},
        "ingress": [
            {"from": [{"podSelector": {"matchLabels": {"role": "api"}}},
                      {"ipBlock": {"cidr": "172.17.0.0/16",
                                   "except": ["172.17.1.0/24"]}}],
             "ports": [{"port": 5432, "protocol": "TCP"}]},
        ],
    },
}


def labels(*strs):
    return LabelArray.parse_select(*strs)


def test_parse_cnp_namespace_scoping():
    rules = parse_cnp(CNP)
    assert len(rules) == 1
    r = rules[0]
    # endpoint selector matches only pods in the prod namespace
    prod_web = labels("k8s:app=web",
                      "k8s:io.kubernetes.pod.namespace=prod")
    other_web = labels("k8s:app=web",
                       "k8s:io.kubernetes.pod.namespace=dev")
    assert r.endpoint_selector.matches(prod_web)
    assert not r.endpoint_selector.matches(other_web)
    # derived policy bookkeeping labels present (delete key)
    assert any(l.key == "io.cilium.k8s.policy.name" and
               l.value == "web-policy" for l in r.labels)
    # from-endpoints got scoped too
    repo = Repository()
    repo.add_list(rules)
    ctx = SearchContext(
        from_labels=labels("k8s:app=client",
                           "k8s:io.kubernetes.pod.namespace=prod"),
        to_labels=prod_web)
    from cilium_tpu.policy.trace import Port
    ctx.dports = [Port(port=80, protocol="TCP")]
    assert str(repo.allows_ingress(ctx)) == "allowed"
    ctx2 = SearchContext(
        from_labels=labels("k8s:app=client",
                           "k8s:io.kubernetes.pod.namespace=dev"),
        to_labels=prod_web, dports=[Port(port=80, protocol="TCP")])
    assert str(repo.allows_ingress(ctx2)) == "denied"


def test_parse_cnp_specs_list_and_errors():
    multi = {"metadata": {"name": "m", "namespace": "x"},
             "specs": [CNP["spec"], CNP["spec"]]}
    assert len(parse_cnp(multi)) == 2
    with pytest.raises(PolicyError):
        parse_cnp({"metadata": {"name": "n"}})  # no spec
    with pytest.raises(PolicyError):
        parse_cnp({"spec": CNP["spec"], "metadata": {}})  # no name


def test_parse_network_policy_peers():
    rules = parse_network_policy(NP)
    assert len(rules) == 1
    r = rules[0]
    # two ingress rules: selector peers (with ports) + cidr peers
    assert len(r.ingress) == 2
    sel_rule = r.ingress[0]
    assert sel_rule.to_ports[0].ports[0].port == "5432"
    api_prod = labels("k8s:role=api",
                      "k8s:io.kubernetes.pod.namespace=prod")
    assert sel_rule.from_endpoints[0].matches(api_prod)
    cidr_rule = r.ingress[1]
    assert cidr_rule.from_cidr_set[0].cidr == "172.17.0.0/16"
    assert cidr_rule.from_cidr_set[0].except_cidrs == ("172.17.1.0/24",)


def test_translate_to_services():
    from cilium_tpu.policy.api import (EgressRule, EndpointSelector,
                                       K8sServiceNamespace, Service)
    rule = Rule(endpoint_selector=EndpointSelector.parse("app=x"),
                egress=[EgressRule(to_services=[Service(
                    k8s_service=K8sServiceNamespace(
                        service_name="db", namespace="prod"))])])
    n = translate_to_services([rule], "db", "prod",
                              ["10.0.0.5", "10.0.0.6"])
    assert n == 1
    cidrs = [c.cidr for c in rule.egress[0].to_cidr_set]
    assert cidrs == ["10.0.0.5/32", "10.0.0.6/32"]
    assert all(c.generated for c in rule.egress[0].to_cidr_set)
    # re-translation replaces this service's entries, not appends
    # (rule_translate.go: delete only generated CIDRs containing the
    # service's old endpoint IPs, then add the new backends)
    translate_to_services([rule], "db", "prod", ["10.0.0.7"],
                          old_backend_ips=["10.0.0.5", "10.0.0.6"])
    assert [c.cidr for c in rule.egress[0].to_cidr_set] == ["10.0.0.7/32"]
    # other services untouched
    assert translate_to_services([rule], "other", "prod", ["1.2.3.4"]) == 0


def test_watcher_drives_daemon():
    d = Daemon(config=DaemonConfig())
    w = K8sWatcher(d)
    try:
        w.on_cnp("added", CNP)
        assert len(d.repo) == 1
        # modify replaces (same name/namespace), not duplicates
        w.on_cnp("modified", CNP)
        assert len(d.repo) == 1
        # endpoints + service -> LB programmed
        w.on_endpoints("added", {
            "metadata": {"name": "db", "namespace": "prod"},
            "subsets": [{"addresses": [{"ip": "10.0.0.5"}],
                         "ports": [{"port": 5432}]}]})
        w.on_service("added", {
            "metadata": {"name": "db", "namespace": "prod"},
            "spec": {"clusterIP": "10.96.0.10",
                     "ports": [{"port": 5432}]}})
        assert len(d.datapath.lb) == 1
        svc = d.datapath.lb.services()[0]
        assert len(svc.backends) == 1
        # delete policy via watcher
        w.on_cnp("deleted", CNP)
        assert len(d.repo) == 0
        w.on_service("deleted", {
            "metadata": {"name": "db", "namespace": "prod"},
            "spec": {"clusterIP": "10.96.0.10",
                     "ports": [{"port": 5432}]}})
        assert len(d.datapath.lb) == 0
        assert w.events_processed == 6
    finally:
        d.shutdown()


def test_watcher_toservices_retranslation():
    d = Daemon(config=DaemonConfig())
    w = K8sWatcher(d)
    try:
        cnp = {
            "metadata": {"name": "svc-egress", "namespace": "prod"},
            "spec": {
                "endpointSelector": {"matchLabels": {"app": "web"}},
                "egress": [{"toServices": [{"k8sService": {
                    "serviceName": "db", "namespace": "prod"}}]}],
            },
        }
        # endpoints known BEFORE policy: translation happens at import
        w.on_endpoints("added", {
            "metadata": {"name": "db", "namespace": "prod"},
            "subsets": [{"addresses": [{"ip": "10.0.0.8"}]}]})
        w.on_cnp("added", cnp)
        rule = d.repo.rules[0]
        assert [c.cidr for c in rule.egress[0].to_cidr_set] == \
            ["10.0.0.8/32"]
        # endpoints change AFTER: rules in the repo re-translate
        w.on_endpoints("added", {
            "metadata": {"name": "db", "namespace": "prod"},
            "subsets": [{"addresses": [{"ip": "10.0.0.9"}]}]})
        rule = d.repo.rules[0]
        assert [c.cidr for c in rule.egress[0].to_cidr_set] == \
            ["10.0.0.9/32"]
    finally:
        d.shutdown()


# ------------------------------------------- widened watcher coverage
# (k8s_watcher.go:70-78,549-560: Pods, Nodes, Namespaces, Ingress
#  informers + per-node CNP status updates)

POD = {
    "metadata": {"name": "web-1", "namespace": "prod",
                 "labels": {"app": "web"}},
    "spec": {},
    "status": {"podIP": "10.30.1.5", "hostIP": "192.168.3.1"},
}


def test_watcher_pod_feeds_ipcache():
    from cilium_tpu.identity import RESERVED_UNMANAGED
    d = Daemon(config=DaemonConfig())
    w = K8sWatcher(d)
    try:
        w.on_pod("added", POD)
        assert d.ipcache.lookup_by_ip("10.30.1.5") == RESERVED_UNMANAGED
        # host-networking pods are skipped (updatePodHostIP)
        w.on_pod("added", {
            "metadata": {"name": "hostpod", "namespace": "prod"},
            "spec": {"hostNetwork": True},
            "status": {"podIP": "192.168.3.1",
                       "hostIP": "192.168.3.1"}})
        assert d.ipcache.lookup_by_ip("192.168.3.1") is None
        w.on_pod("deleted", POD)
        assert d.ipcache.lookup_by_ip("10.30.1.5") is None
        assert w.events_by_kind["pod"] == 3
    finally:
        d.shutdown()


def test_watcher_pod_label_update_changes_endpoint_identity():
    d = Daemon(config=DaemonConfig())
    w = K8sWatcher(d)
    try:
        ep = d.endpoint_create(1, ipv4="10.30.1.5",
                               container_name="prod/web-1",
                               labels=["k8s:app=web"])
        ident_before = ep.security_identity
        relabeled = {
            "metadata": {"name": "web-1", "namespace": "prod",
                         "labels": {"app": "web", "tier": "gold"}},
            "spec": {},
            "status": {"podIP": "10.30.1.5",
                       "hostIP": "192.168.3.1"}}
        w.on_pod("modified", relabeled)
        assert ep.security_identity != ident_before
        assert any(lb.key == "tier" for lb in ep.labels.values())
    finally:
        d.shutdown()


def test_watcher_node_programs_tunnel():
    d = Daemon(config=DaemonConfig())
    w = K8sWatcher(d)
    try:
        w.on_node("added", {
            "metadata": {"name": "worker-2"},
            "spec": {"podCIDR": "10.31.0.0/24"},
            "status": {"addresses": [
                {"type": "InternalIP", "address": "192.168.3.2"}]}})
        assert "10.31.0.0/24" in d.datapath.tunnel_prefixes
        assert d.node_manager.tunnel_map["10.31.0.0/24"] == \
            "192.168.3.2"
        w.on_node("deleted", {"metadata": {"name": "worker-2"}})
        assert d.datapath.tunnel_prefixes == {}
    finally:
        d.shutdown()


def test_watcher_namespace_labels_reresolve_endpoints():
    d = Daemon(config=DaemonConfig())
    w = K8sWatcher(d)
    try:
        ep = d.endpoint_create(1, ipv4="10.30.1.6",
                               container_name="prod/web-2",
                               labels=["k8s:app=web"])
        ident_before = ep.security_identity
        w.on_namespace("added", {
            "metadata": {"name": "prod",
                         "labels": {"env": "production"}}})
        assert ep.security_identity != ident_before
        ns_keys = [lb.key for lb in ep.labels.values()]
        assert any("namespace.labels.env" in k for k in ns_keys)
        # same labels again: no further identity churn
        ident_stable = ep.security_identity
        w.on_namespace("modified", {
            "metadata": {"name": "prod",
                         "labels": {"env": "production"}}})
        assert ep.security_identity == ident_stable
    finally:
        d.shutdown()


def test_watcher_ingress_programs_external_frontend():
    d = Daemon(config=DaemonConfig())
    w = K8sWatcher(d, ingress_host_ip="192.0.2.1")
    try:
        w.on_service("added", {
            "metadata": {"name": "web", "namespace": "prod"},
            "spec": {"clusterIP": "10.96.0.30",
                     "ports": [{"port": 8080}]}})
        w.on_endpoints("added", {
            "metadata": {"name": "web", "namespace": "prod"},
            "subsets": [{"addresses": [{"ip": "10.30.1.7"}],
                         "ports": [{"port": 8080}]}]})
        w.on_ingress("added", {
            "metadata": {"name": "web-ing", "namespace": "prod"},
            "spec": {"backend": {"serviceName": "web",
                                 "servicePort": 8080}}})
        from cilium_tpu.compiler.lpm import ipv4_to_u32
        ing_vip = ipv4_to_u32("192.0.2.1")
        svcs = [s for s in d.datapath.lb.services() if s.vip == ing_vip]
        assert svcs and svcs[0].port == 8080 and \
            len(svcs[0].backends) == 1
        w.on_ingress("deleted", {
            "metadata": {"name": "web-ing", "namespace": "prod"},
            "spec": {"backend": {"serviceName": "web",
                                 "servicePort": 8080}}})
        assert not [s for s in d.datapath.lb.services()
                    if s.vip == ing_vip]
    finally:
        d.shutdown()


def test_watcher_headless_service_not_programmed():
    d = Daemon(config=DaemonConfig())
    w = K8sWatcher(d)
    try:
        w.on_service("added", {
            "metadata": {"name": "hs", "namespace": "prod"},
            "spec": {"clusterIP": "None", "ports": [{"port": 9042}]}})
        assert len(d.datapath.lb) == 0  # never programmed into the LB
        assert w._services[("prod", "hs")]["headless"] is True
        w.on_service("deleted", {
            "metadata": {"name": "hs", "namespace": "prod"},
            "spec": {"clusterIP": "None", "ports": [{"port": 9042}]}})
        assert ("prod", "hs") not in w._services
    finally:
        d.shutdown()


def test_watcher_cnp_node_status():
    import time as _t
    d = Daemon(config=DaemonConfig())
    w = K8sWatcher(d)
    try:
        w.on_cnp("added", CNP)
        st = w.get_cnp_status("prod", "web-policy")
        assert d.node_name in st
        node_st = st[d.node_name]
        assert node_st["ok"] and "revision" in node_st
        # enforcement status flips once endpoints realize the revision
        deadline = _t.time() + 10
        while _t.time() < deadline:
            node_st = w.get_cnp_status("prod",
                                       "web-policy")[d.node_name]
            if node_st["enforcing"]:
                break
            _t.sleep(0.05)
        assert node_st["enforcing"]
        # a broken CNP reports the import error instead
        w.on_cnp("added", {
            "metadata": {"name": "bad", "namespace": "prod"},
            "spec": {"endpointSelector": {"matchLabels": {"a": "b"}},
                     "ingress": [{"fromCIDR": ["not-a-cidr"]}]}})
        bad = w.get_cnp_status("prod", "bad")[d.node_name]
        assert not bad["ok"] and "error" in bad
        # deletion clears the status
        w.on_cnp("deleted", CNP)
        assert w.get_cnp_status("prod", "web-policy") == {}
    finally:
        d.shutdown()


def test_watcher_ingress_resync_and_target_port():
    """Review regressions: ingress frontends follow Endpoints churn,
    use the service's targetPort, and a servicePort change drops the
    old frontend."""
    d = Daemon(config=DaemonConfig())
    w = K8sWatcher(d, ingress_host_ip="192.0.2.1")
    try:
        # ingress BEFORE endpoints exist: programmed with 0 backends
        w.on_service("added", {
            "metadata": {"name": "web", "namespace": "prod"},
            "spec": {"clusterIP": "10.96.0.20",
                     "ports": [{"port": 80, "targetPort": 8080}]}})
        w.on_ingress("added", {
            "metadata": {"name": "ing", "namespace": "prod"},
            "spec": {"backend": {"serviceName": "web",
                                 "servicePort": 80}}})
        # endpoints arrive later: the frontend is resynced with the
        # targetPort-resolved backends
        w.on_endpoints("added", {
            "metadata": {"name": "web", "namespace": "prod"},
            "subsets": [{"addresses": [{"ip": "10.30.2.1"}],
                         "ports": [{"port": 8080}]}]})
        from cilium_tpu.compiler.lpm import ipv4_to_u32
        ing = [s for s in d.datapath.lb.services()
               if s.vip == ipv4_to_u32("192.0.2.1")]
        assert ing and len(ing[0].backends) == 1
        assert ing[0].backends[0].port == 8080  # targetPort, not 80
        # servicePort change: old frontend removed, new programmed
        w.on_ingress("modified", {
            "metadata": {"name": "ing", "namespace": "prod"},
            "spec": {"backend": {"serviceName": "web",
                                 "servicePort": 81}}})
        ports = [s.port for s in d.datapath.lb.services()
                 if s.vip == ipv4_to_u32("192.0.2.1")]
        assert ports == [81]
    finally:
        d.shutdown()


def test_watcher_pod_ip_change_cleans_stale_entry():
    from cilium_tpu.identity import RESERVED_UNMANAGED
    d = Daemon(config=DaemonConfig())
    w = K8sWatcher(d)
    try:
        w.on_pod("added", POD)
        assert d.ipcache.lookup_by_ip("10.30.1.5") == RESERVED_UNMANAGED
        moved = {"metadata": {"name": "web-1", "namespace": "prod"},
                 "spec": {},
                 "status": {"podIP": "10.30.1.99",
                            "hostIP": "192.168.3.1"}}
        w.on_pod("modified", moved)
        assert d.ipcache.lookup_by_ip("10.30.1.5") is None  # stale gone
        assert d.ipcache.lookup_by_ip("10.30.1.99") == RESERVED_UNMANAGED
        w.on_pod("deleted", moved)
        assert d.ipcache.lookup_by_ip("10.30.1.99") is None
    finally:
        d.shutdown()


def test_watcher_label_updates_preserve_non_k8s_labels():
    d = Daemon(config=DaemonConfig())
    w = K8sWatcher(d)
    try:
        ep = d.endpoint_create(
            1, ipv4="10.30.1.8", container_name="prod/web-3",
            labels=["k8s:app=web", "container:runtime=docker"])
        w.on_namespace("added", {
            "metadata": {"name": "prod", "labels": {"env": "prod"}}})
        srcs = {lb.source for lb in ep.labels.values()}
        assert "container" in srcs  # non-k8s label survived
        w.on_pod("modified", {
            "metadata": {"name": "web-3", "namespace": "prod",
                         "labels": {"app": "web", "v": "2"}},
            "spec": {}, "status": {"podIP": "10.30.1.8",
                                   "hostIP": "192.168.3.1"}})
        srcs = {lb.source for lb in ep.labels.values()}
        assert "container" in srcs
        assert any(lb.key == "v" for lb in ep.labels.values())
    finally:
        d.shutdown()


def test_watcher_service_port_removal_and_ingress_teardown():
    """Review regressions: a modified service spec that drops a port
    tears that frontend down, and deleting the backing service tears
    dependent ingress frontends down instead of re-programming them
    with a guessed target port."""
    from cilium_tpu.compiler.lpm import ipv4_to_u32
    d = Daemon(config=DaemonConfig())
    w = K8sWatcher(d, ingress_host_ip="192.0.2.1")
    try:
        w.on_service("added", {
            "metadata": {"name": "multi", "namespace": "prod"},
            "spec": {"clusterIP": "10.96.0.40",
                     "ports": [{"port": 80, "targetPort": 8080},
                               {"port": 443, "targetPort": 8443}]}})
        vip = ipv4_to_u32("10.96.0.40")
        assert {s.port for s in d.datapath.lb.services()
                if s.vip == vip} == {80, 443}
        # modified spec drops 443
        w.on_service("modified", {
            "metadata": {"name": "multi", "namespace": "prod"},
            "spec": {"clusterIP": "10.96.0.40",
                     "ports": [{"port": 80, "targetPort": 8080}]}})
        assert {s.port for s in d.datapath.lb.services()
                if s.vip == vip} == {80}
        # ingress on the service, then the service is deleted: the
        # ingress frontend goes away too
        w.on_ingress("added", {
            "metadata": {"name": "ing", "namespace": "prod"},
            "spec": {"backend": {"serviceName": "multi",
                                 "servicePort": 80}}})
        ing_vip = ipv4_to_u32("192.0.2.1")
        assert [s for s in d.datapath.lb.services()
                if s.vip == ing_vip]
        w.on_service("deleted", {
            "metadata": {"name": "multi", "namespace": "prod"},
            "spec": {"clusterIP": "10.96.0.40",
                     "ports": [{"port": 80, "targetPort": 8080}]}})
        assert not [s for s in d.datapath.lb.services()
                    if s.vip == ing_vip]
    finally:
        d.shutdown()
