"""k8s policy parsing, ToServices translation, and watcher-driven agent.

Mirrors pkg/k8s tests: CNP parse fixtures (network_policy.go tests),
namespace scoping, NetworkPolicy peers, rule_translate.
"""

import numpy as np
import pytest

from cilium_tpu.daemon import Daemon
from cilium_tpu.k8s import (K8sWatcher, parse_cnp, parse_network_policy,
                            translate_to_services)
from cilium_tpu.labels import LabelArray
from cilium_tpu.policy.api import PolicyError, Rule
from cilium_tpu.policy.repository import Repository
from cilium_tpu.policy.trace import SearchContext
from cilium_tpu.utils.option import DaemonConfig

CNP = {
    "apiVersion": "cilium.io/v2",
    "kind": "CiliumNetworkPolicy",
    "metadata": {"name": "web-policy", "namespace": "prod"},
    "spec": {
        "endpointSelector": {"matchLabels": {"app": "web"}},
        "ingress": [
            {"fromEndpoints": [{"matchLabels": {"app": "client"}}],
             "toPorts": [{"ports": [{"port": "80", "protocol": "TCP"}]}]},
        ],
    },
}

NP = {
    "apiVersion": "networking.k8s.io/v1",
    "kind": "NetworkPolicy",
    "metadata": {"name": "db-np", "namespace": "prod"},
    "spec": {
        "podSelector": {"matchLabels": {"role": "db"}},
        "ingress": [
            {"from": [{"podSelector": {"matchLabels": {"role": "api"}}},
                      {"ipBlock": {"cidr": "172.17.0.0/16",
                                   "except": ["172.17.1.0/24"]}}],
             "ports": [{"port": 5432, "protocol": "TCP"}]},
        ],
    },
}


def labels(*strs):
    return LabelArray.parse_select(*strs)


def test_parse_cnp_namespace_scoping():
    rules = parse_cnp(CNP)
    assert len(rules) == 1
    r = rules[0]
    # endpoint selector matches only pods in the prod namespace
    prod_web = labels("k8s:app=web",
                      "k8s:io.kubernetes.pod.namespace=prod")
    other_web = labels("k8s:app=web",
                       "k8s:io.kubernetes.pod.namespace=dev")
    assert r.endpoint_selector.matches(prod_web)
    assert not r.endpoint_selector.matches(other_web)
    # derived policy bookkeeping labels present (delete key)
    assert any(l.key == "io.cilium.k8s.policy.name" and
               l.value == "web-policy" for l in r.labels)
    # from-endpoints got scoped too
    repo = Repository()
    repo.add_list(rules)
    ctx = SearchContext(
        from_labels=labels("k8s:app=client",
                           "k8s:io.kubernetes.pod.namespace=prod"),
        to_labels=prod_web)
    from cilium_tpu.policy.trace import Port
    ctx.dports = [Port(port=80, protocol="TCP")]
    assert str(repo.allows_ingress(ctx)) == "allowed"
    ctx2 = SearchContext(
        from_labels=labels("k8s:app=client",
                           "k8s:io.kubernetes.pod.namespace=dev"),
        to_labels=prod_web, dports=[Port(port=80, protocol="TCP")])
    assert str(repo.allows_ingress(ctx2)) == "denied"


def test_parse_cnp_specs_list_and_errors():
    multi = {"metadata": {"name": "m", "namespace": "x"},
             "specs": [CNP["spec"], CNP["spec"]]}
    assert len(parse_cnp(multi)) == 2
    with pytest.raises(PolicyError):
        parse_cnp({"metadata": {"name": "n"}})  # no spec
    with pytest.raises(PolicyError):
        parse_cnp({"spec": CNP["spec"], "metadata": {}})  # no name


def test_parse_network_policy_peers():
    rules = parse_network_policy(NP)
    assert len(rules) == 1
    r = rules[0]
    # two ingress rules: selector peers (with ports) + cidr peers
    assert len(r.ingress) == 2
    sel_rule = r.ingress[0]
    assert sel_rule.to_ports[0].ports[0].port == "5432"
    api_prod = labels("k8s:role=api",
                      "k8s:io.kubernetes.pod.namespace=prod")
    assert sel_rule.from_endpoints[0].matches(api_prod)
    cidr_rule = r.ingress[1]
    assert cidr_rule.from_cidr_set[0].cidr == "172.17.0.0/16"
    assert cidr_rule.from_cidr_set[0].except_cidrs == ("172.17.1.0/24",)


def test_translate_to_services():
    from cilium_tpu.policy.api import (EgressRule, EndpointSelector,
                                       K8sServiceNamespace, Service)
    rule = Rule(endpoint_selector=EndpointSelector.parse("app=x"),
                egress=[EgressRule(to_services=[Service(
                    k8s_service=K8sServiceNamespace(
                        service_name="db", namespace="prod"))])])
    n = translate_to_services([rule], "db", "prod",
                              ["10.0.0.5", "10.0.0.6"])
    assert n == 1
    cidrs = [c.cidr for c in rule.egress[0].to_cidr_set]
    assert cidrs == ["10.0.0.5/32", "10.0.0.6/32"]
    assert all(c.generated for c in rule.egress[0].to_cidr_set)
    # re-translation replaces this service's entries, not appends
    # (rule_translate.go: delete only generated CIDRs containing the
    # service's old endpoint IPs, then add the new backends)
    translate_to_services([rule], "db", "prod", ["10.0.0.7"],
                          old_backend_ips=["10.0.0.5", "10.0.0.6"])
    assert [c.cidr for c in rule.egress[0].to_cidr_set] == ["10.0.0.7/32"]
    # other services untouched
    assert translate_to_services([rule], "other", "prod", ["1.2.3.4"]) == 0


def test_watcher_drives_daemon():
    d = Daemon(config=DaemonConfig())
    w = K8sWatcher(d)
    try:
        w.on_cnp("added", CNP)
        assert len(d.repo) == 1
        # modify replaces (same name/namespace), not duplicates
        w.on_cnp("modified", CNP)
        assert len(d.repo) == 1
        # endpoints + service -> LB programmed
        w.on_endpoints("added", {
            "metadata": {"name": "db", "namespace": "prod"},
            "subsets": [{"addresses": [{"ip": "10.0.0.5"}],
                         "ports": [{"port": 5432}]}]})
        w.on_service("added", {
            "metadata": {"name": "db", "namespace": "prod"},
            "spec": {"clusterIP": "10.96.0.10",
                     "ports": [{"port": 5432}]}})
        assert len(d.datapath.lb) == 1
        svc = d.datapath.lb.services()[0]
        assert len(svc.backends) == 1
        # delete policy via watcher
        w.on_cnp("deleted", CNP)
        assert len(d.repo) == 0
        w.on_service("deleted", {
            "metadata": {"name": "db", "namespace": "prod"},
            "spec": {"clusterIP": "10.96.0.10",
                     "ports": [{"port": 5432}]}})
        assert len(d.datapath.lb) == 0
        assert w.events_processed == 6
    finally:
        d.shutdown()


def test_watcher_toservices_retranslation():
    d = Daemon(config=DaemonConfig())
    w = K8sWatcher(d)
    try:
        cnp = {
            "metadata": {"name": "svc-egress", "namespace": "prod"},
            "spec": {
                "endpointSelector": {"matchLabels": {"app": "web"}},
                "egress": [{"toServices": [{"k8sService": {
                    "serviceName": "db", "namespace": "prod"}}]}],
            },
        }
        # endpoints known BEFORE policy: translation happens at import
        w.on_endpoints("added", {
            "metadata": {"name": "db", "namespace": "prod"},
            "subsets": [{"addresses": [{"ip": "10.0.0.8"}]}]})
        w.on_cnp("added", cnp)
        rule = d.repo.rules[0]
        assert [c.cidr for c in rule.egress[0].to_cidr_set] == \
            ["10.0.0.8/32"]
        # endpoints change AFTER: rules in the repo re-translate
        w.on_endpoints("added", {
            "metadata": {"name": "db", "namespace": "prod"},
            "subsets": [{"addresses": [{"ip": "10.0.0.9"}]}]})
        rule = d.repo.rules[0]
        assert [c.cidr for c in rule.egress[0].to_cidr_set] == \
            ["10.0.0.9/32"]
    finally:
        d.shutdown()
