"""k8s list/watch transport: reflectors over HTTP against the in-repo
fake apiserver (round-5 VERDICT #5).

The watcher's semantics (ordering, rv dedup, handlers) were already
tested via direct injection; this suite proves the TRANSPORT — LIST,
chunked WATCH streams, reconnect-from-last-version on stream loss, and
the 410-Gone full-relist path — end to end into a real Daemon.
Reference: daemon/k8s_watcher.go:70-78 client-go informers.
"""

import time

import numpy as np
import pytest

from cilium_tpu.daemon import Daemon
from cilium_tpu.datapath.engine import make_full_batch
from cilium_tpu.k8s import K8sWatcher
from cilium_tpu.k8s.client import (GoneError, K8sClient, K8sTransport,
                                   Reflector)
from cilium_tpu.k8s.fake_apiserver import FakeAPIServer
from cilium_tpu.utils.option import DaemonConfig

CNP_PATH = "/apis/cilium.io/v2/ciliumnetworkpolicies"
POD_PATH = "/api/v1/pods"


def _cnp(name="web-policy", port="80", ns="prod", app="web"):
    return {
        "apiVersion": "cilium.io/v2",
        "kind": "CiliumNetworkPolicy",
        "metadata": {"name": name, "namespace": ns},
        "spec": {
            "endpointSelector": {"matchLabels": {"app": app}},
            "ingress": [
                {"fromEndpoints": [
                    {"matchLabels": {"app": "client"}}],
                 "toPorts": [{"ports": [
                     {"port": port, "protocol": "TCP"}]}]},
            ],
        },
    }


def _pod(name, ip, ns="prod", labels=None):
    return {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": name, "namespace": ns,
                     "labels": labels or {"app": "web"}},
        "status": {"podIP": ip, "hostIP": "192.168.1.10",
                   "phase": "Running"},
        "spec": {},
    }


@pytest.fixture()
def fake():
    srv = FakeAPIServer().start()
    yield srv
    srv.shutdown()


@pytest.fixture()
def daemon():
    d = Daemon(config=DaemonConfig(state_dir=""))
    yield d
    d.shutdown()


def _wait(fn, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(0.02)
    return fn()


# ------------------------------------------------------------ raw client

def test_client_list_and_watch_stream(fake):
    c = K8sClient(fake.base_url)
    fake.upsert("ciliumnetworkpolicies", _cnp("a"))
    items, rv = c.list(CNP_PATH)
    assert len(items) == 1 and items[0]["metadata"]["name"] == "a"

    got = []

    def consume():
        for etype, obj in c.watch(CNP_PATH, rv):
            got.append((etype, obj["metadata"]["name"]))
            if len(got) >= 3:
                return

    import threading
    t = threading.Thread(target=consume, daemon=True)
    t.start()
    time.sleep(0.2)
    fake.upsert("ciliumnetworkpolicies", _cnp("b"))
    fake.upsert("ciliumnetworkpolicies", _cnp("b", port="81"))
    fake.delete("ciliumnetworkpolicies", "prod", "b")
    t.join(timeout=10)
    assert got == [("ADDED", "b"), ("MODIFIED", "b"), ("DELETED", "b")]


def test_watch_from_compacted_version_is_gone(fake):
    c = K8sClient(fake.base_url)
    fake.upsert("ciliumnetworkpolicies", _cnp("a"))
    fake.upsert("ciliumnetworkpolicies", _cnp("b"))
    fake.compact()
    with pytest.raises(GoneError):
        for _ in c.watch(CNP_PATH, "1"):
            pass


# ----------------------------------------------------------- reflector

def test_reflector_feeds_watcher_and_daemon_enforces(fake, daemon):
    """The full informer path: object lands in the fake apiserver ->
    LIST/WATCH -> K8sWatcher -> policy repo -> device verdict."""
    web = daemon.endpoint_create(1, ipv4="10.0.0.31",
                                 labels=["k8s:app=client",
                                         "k8s:io.kubernetes.pod."
                                         "namespace=prod"])
    db = daemon.endpoint_create(2, ipv4="10.0.0.32",
                                labels=["k8s:app=web",
                                        "k8s:io.kubernetes.pod."
                                        "namespace=prod"])
    kw = K8sWatcher(daemon)
    transport = K8sTransport(kw, fake.base_url)
    try:
        transport.start()
        assert transport.wait_synced(10)
        fake.upsert("ciliumnetworkpolicies", _cnp())
        assert _wait(lambda: kw.events_by_kind.get("cnp", 0) >= 1)
        assert kw.wait_idle(10)
        assert daemon.wait_for_policy_revision()
        slot = db.table_slot
        batch = make_full_batch(
            endpoint=[slot, slot], saddr=["10.0.0.31", "10.0.0.31"],
            daddr=["10.0.0.32", "10.0.0.32"], sport=[40100, 40101],
            dport=[80, 22], direction=[0, 0])
        v, *_ = daemon.datapath.process(batch)
        assert int(np.asarray(v)[0]) >= 0   # allowed by the CNP
        assert int(np.asarray(v)[1]) < 0    # not in the CNP
        # deletion propagates too
        fake.delete("ciliumnetworkpolicies", "prod", "web-policy")
        assert _wait(lambda: kw.events_by_kind.get("cnp", 0) >= 2)
        assert kw.wait_idle(10)
        assert _wait(lambda: daemon.repo.revision >= 3)
    finally:
        transport.stop()
        kw.stop()


def test_reflector_reconnects_after_stream_drop(fake, daemon):
    """Network blip: the server drops every watch stream; the
    reflector re-watches from its last seen version and events created
    during the gap still arrive, without a relist."""
    kw = K8sWatcher(daemon)
    r = Reflector(K8sClient(fake.base_url), POD_PATH, "pod", kw).start()
    try:
        assert r.synced.wait(10)
        fake.upsert("pods", _pod("p1", "10.0.0.41"))
        assert _wait(lambda: kw.events_by_kind.get("pod", 0) >= 1)
        relists_before = r.relists

        fake.disconnect_watchers()
        # during the "outage" (between streams) an event happens
        fake.upsert("pods", _pod("p2", "10.0.0.42"))
        assert _wait(lambda: kw.events_by_kind.get("pod", 0) >= 2)
        assert _wait(lambda: r.rewatches >= 2)
        assert r.relists == relists_before, \
            "stream drop must resume from last rv, not relist"
        assert daemon.ipcache.lookup_by_ip("10.0.0.42") is not None
    finally:
        r.stop()
        kw.stop()


def test_reflector_410_gone_triggers_full_relist(fake, daemon):
    """Compaction: watch from a stale version answers 410; the
    reflector relists and converges, including deletions that happened
    while it was disconnected (DeletedFinalStateUnknown analog)."""
    kw = K8sWatcher(daemon)
    fake.upsert("pods", _pod("stay", "10.0.0.51"))
    fake.upsert("pods", _pod("doomed", "10.0.0.52"))
    r = Reflector(K8sClient(fake.base_url), POD_PATH, "pod", kw).start()
    try:
        assert r.synced.wait(10)
        assert _wait(
            lambda: daemon.ipcache.lookup_by_ip("10.0.0.52") is not None)
        relists_before = r.relists

        # simulate a long partition: stream dies, history is compacted,
        # and the cluster changes shape meanwhile
        fake.delete("pods", "prod", "doomed")
        fake.upsert("pods", _pod("newcomer", "10.0.0.53"))
        fake.compact()
        fake.disconnect_watchers()

        assert _wait(lambda: r.relists > relists_before), \
            "410 must force a relist"
        assert _wait(
            lambda: daemon.ipcache.lookup_by_ip("10.0.0.53") is not None)
        # the deletion during the partition was reconstructed by the
        # relist diff
        assert _wait(
            lambda: daemon.ipcache.lookup_by_ip("10.0.0.52") is None)
        assert daemon.ipcache.lookup_by_ip("10.0.0.51") is not None
    finally:
        r.stop()
        kw.stop()


def test_relist_resync_is_deduped_by_resource_version(fake, daemon):
    """A relist re-delivers every object; the watcher's rv dedup must
    drop the unchanged ones instead of re-applying handlers."""
    kw = K8sWatcher(daemon)
    fake.upsert("pods", _pod("p1", "10.0.0.61"))
    r = Reflector(K8sClient(fake.base_url), POD_PATH, "pod", kw).start()
    try:
        assert r.synced.wait(10)
        assert _wait(lambda: kw.events_by_kind.get("pod", 0) == 1)
        applied_before = kw.events_by_kind.get("pod", 0)
        # force a pod relist without POD churn: advance the global
        # resourceVersion via another resource, then compact — the pod
        # watcher's version now predates the compaction (410), but the
        # relist re-delivers only the unchanged p1
        fake.upsert("services", {
            "metadata": {"name": "svc", "namespace": "prod"},
            "spec": {"clusterIP": "10.96.0.99",
                     "ports": [{"port": 80, "protocol": "TCP"}]}})
        fake.compact()
        fake.disconnect_watchers()
        assert _wait(lambda: r.relists >= 2)
        time.sleep(0.3)
        assert kw.events_by_kind.get("pod", 0) == applied_before, \
            "unchanged object re-applied on resync"
    finally:
        r.stop()
        kw.stop()


def test_transport_stop_terminates_reflector_threads(fake, daemon):
    kw = K8sWatcher(daemon)
    transport = K8sTransport(kw, fake.base_url).start()
    assert transport.wait_synced(10)
    transport.stop()
    stuck = [r for r in transport.reflectors if r._thread.is_alive()]
    if stuck:
        import sys
        import traceback
        frames = sys._current_frames()
        detail = "\n".join(
            f"--- {r.kind}\n" + "".join(
                traceback.format_stack(frames[r._thread.ident]))
            for r in stuck if r._thread.ident in frames)
        raise AssertionError(
            f"stuck reflectors {[r.kind for r in stuck]}:\n{detail}")
    kw.stop()
