"""Conntrack semantics tests (mirrors bpf/lib/conntrack.h behavior and
the ctmap GC sweep)."""

import numpy as np
import jax.numpy as jnp

from cilium_tpu.datapath.conntrack import (CT_CLOSE_TIMEOUT, CT_EGRESS,
                                           CT_ESTABLISHED, CT_INGRESS,
                                           CT_LIFETIME_NONTCP,
                                           CT_LIFETIME_TCP, CT_NEW,
                                           CT_RELATED, CT_REPLY, CTBatch,
                                           ConntrackTable, TCP_ACK, TCP_FIN,
                                           TCP_RST, TCP_SYN)


def mkbatch(saddr, daddr, sport, dport, proto=None, direction=None,
            tcp_flags=None, related=None):
    n = len(saddr)
    arr = lambda x, d: jnp.asarray(np.asarray(
        x if x is not None else np.full(n, d), np.int32))
    return CTBatch(saddr=arr(saddr, 0), daddr=arr(daddr, 0),
                   sport=arr(sport, 0), dport=arr(dport, 0),
                   proto=arr(proto, 6), direction=arr(direction, CT_EGRESS),
                   tcp_flags=arr(tcp_flags, TCP_SYN),
                   related=arr(related, 0))


def test_new_then_established():
    ct = ConntrackTable(slots=1024)
    b = mkbatch([0x0A000001], [0x0A000002], [4242], [80])
    v, _ = ct.step(b, now=100)
    assert int(v[0]) == CT_NEW
    assert ct.entry_count() == 1
    # same flow again: established
    b2 = mkbatch([0x0A000001], [0x0A000002], [4242], [80],
                 tcp_flags=[TCP_ACK])
    v, _ = ct.step(b2, now=101)
    assert int(v[0]) == CT_ESTABLISHED


def test_reply_direction():
    ct = ConntrackTable(slots=1024)
    # egress flow created by the container
    ct.step(mkbatch([0x0A000001], [0x0A000002], [4242], [80]), now=100)
    # reply: reversed tuple, opposite direction
    reply = mkbatch([0x0A000002], [0x0A000001], [80], [4242],
                    direction=[CT_INGRESS], tcp_flags=[TCP_SYN | TCP_ACK])
    v, _ = ct.step(reply, now=101)
    assert int(v[0]) == CT_REPLY


def test_related_icmp():
    ct = ConntrackTable(slots=1024)
    ct.step(mkbatch([0x0A000001], [0x0A000002], [4242], [80]), now=100)
    # ICMP error about the flow: reverse lookup with related flag
    rel = mkbatch([0x0A000002], [0x0A000001], [80], [4242],
                  direction=[CT_INGRESS], proto=[1], tcp_flags=[0],
                  related=[1])
    # ICMP uses same addrs; ports carried from original tuple context
    v, _ = ct.step(mkbatch([0x0A000002], [0x0A000001], [80], [4242],
                           direction=[CT_INGRESS], related=[1]), now=101)
    assert int(v[0]) == CT_RELATED


def test_create_mask_gates_creation():
    ct = ConntrackTable(slots=1024)
    b = mkbatch([1], [2], [3], [4])
    v, _ = ct.step(b, now=10, create_mask=jnp.zeros(1, bool))
    assert int(v[0]) == CT_NEW
    assert ct.entry_count() == 0


def test_expiry_and_gc():
    ct = ConntrackTable(slots=1024)
    # UDP flow: 60s lifetime (conntrack.h:32)
    ct.step(mkbatch([1], [2], [3], [4], proto=[17], tcp_flags=[0]), now=100)
    assert ct.entry_count() == 1
    # before expiry: established
    v, _ = ct.step(mkbatch([1], [2], [3], [4], proto=[17], tcp_flags=[0]),
                   now=100 + CT_LIFETIME_NONTCP - 1)
    assert int(v[0]) == CT_ESTABLISHED
    # after expiry: new again
    v, _ = ct.step(mkbatch([1], [2], [3], [4], proto=[17], tcp_flags=[0]),
                   now=100 + 2 * CT_LIFETIME_NONTCP + 2,
                   create_mask=jnp.zeros(1, bool))
    assert int(v[0]) == CT_NEW
    # gc removes it
    n = ct.gc(now=100 + 3 * CT_LIFETIME_NONTCP)
    assert n == 1
    assert ct.entry_count() == 0


def test_fin_shortens_lifetime():
    ct = ConntrackTable(slots=1024)
    ct.step(mkbatch([1], [2], [3], [4], tcp_flags=[TCP_SYN | TCP_ACK]),
            now=100)
    # FIN: close timeout (10s)
    ct.step(mkbatch([1], [2], [3], [4], tcp_flags=[TCP_FIN | TCP_ACK]),
            now=200)
    v, _ = ct.step(mkbatch([1], [2], [3], [4], tcp_flags=[TCP_ACK]),
                   now=200 + CT_CLOSE_TIMEOUT + 1,
                   create_mask=jnp.zeros(1, bool))
    assert int(v[0]) == CT_NEW  # entry expired after close timeout


def test_batch_many_flows():
    ct = ConntrackTable(slots=1 << 14)
    rng = np.random.default_rng(0)
    n = 2000
    saddr = rng.integers(1, 2**31, n).astype(np.int32)
    daddr = rng.integers(1, 2**31, n).astype(np.int32)
    sport = rng.integers(1024, 65536, n).astype(np.int32)
    dport = np.full(n, 443, np.int32)
    b = mkbatch(saddr, daddr, sport, dport)
    v, _ = ct.step(b, now=100)
    assert (np.asarray(v) == CT_NEW).all()
    # nearly all created (within-batch slot races may drop a handful)
    assert ct.entry_count() >= n - 20
    v, _ = ct.step(b, now=101)
    assert (np.asarray(v) == CT_ESTABLISHED).mean() > 0.99


def test_rev_nat_stamp_and_return():
    ct = ConntrackTable(slots=1024)
    b = mkbatch([0x0A000001], [0x0A000002], [4242], [80])
    ct.step(b, now=100)
    ct.stamp_rev_nat(b, jnp.asarray(np.array([7], np.int32)), now=100)
    # reply carries the rev-NAT index back
    reply = mkbatch([0x0A000002], [0x0A000001], [80], [4242],
                    direction=[CT_INGRESS])
    v, rn = ct.step(reply, now=101)
    assert int(v[0]) == CT_REPLY
    assert int(rn[0]) == 7
